"""jaxguard SPMD passes: host-divergence taint (JG001) + collective
schedules (JG002).

The multi-host execution model this framework runs under (docs/DESIGN.md
"Elastic pod training") is lockstep-collective: every host traces the
same Python, compiles the same program, and issues the same collectives
in the same order.  Anything that lets two hosts take different paths to
a collective — a wall-clock comparison, an env var, a per-host HBM probe
— is a *silent deadlock*: the job hangs at the first mismatched
collective with no error on any host.  PR 11 built the sanctioned escape
hatch (``parallel/consensus.replicated_decision``: one allgather + a
deterministic reduce, so the *decision* is replicated even when its
inputs are not); this module is the static policeman that everything
else goes through it.

Two passes:

* **JG001** (AST, this module): flow-sensitive taint from host-divergent
  sources (``time.*``, ``os.environ``, ``random``, ``process_index``,
  filesystem stats, psutil/HBM probes) into control flow that gates a
  collective-issuing call.  Routing a tainted value *through*
  ``replicated_decision`` clears the taint — the allowlist is
  load-bearing, exactly like JA002's accumulation allowlist: the
  framework's own ``auto_plan`` is clean *because* it launders its HBM
  probe through the consensus primitive, and deleting that call makes
  this rule fire.
* **JG002** (IR, pure comparison here — extraction lives in
  :func:`ir.mesh_axis_collective_schedule`): two programs that hosts
  could run as alternates of the same dispatch point must issue the
  identical *ordered* collective sequence on every mesh axis they
  share, or the first mismatched collective deadlocks the pod.  Pairs
  that legitimately differ (the plan ladder's rungs — that is WHY the
  rung vote exists) are declared divergent in the checked-in guard
  schedule contract; the declaration is itself policed for staleness.

Import-light on purpose (stdlib only), like :mod:`core`: the AST pass
must run in pre-commit hooks without initializing a backend.
"""

from __future__ import annotations

import ast
import itertools
import re

from .core import Finding, dotted_name, target_names

# --------------------------------------------------------------- JG001 model

#: dotted-name prefixes whose calls/reads produce host-divergent values
_SOURCE_PREFIXES = (
    "time.", "os.environ", "random.", "np.random.", "numpy.random.",
    "psutil.", "glob.",
)

#: exact dotted names (or bare names, for ``from x import y`` styles)
_SOURCE_NAMES = frozenset({
    "os.getenv", "os.stat", "os.lstat", "os.listdir", "os.scandir",
    "os.statvfs", "os.path.exists", "os.path.isfile", "os.path.isdir",
    "os.path.getsize", "os.path.getmtime", "os.path.getctime",
    "os.path.getatime", "shutil.disk_usage", "socket.gethostname",
    "platform.node", "uuid.uuid1", "uuid.uuid4",
    "jax.process_index", "jax.host_id", "process_index", "host_id",
    "detect_hbm_bytes", "perf_counter", "monotonic", "time_ns",
})

#: method names divergent on ANY receiver: device HBM probes and
#: pathlib-style filesystem stats
_SOURCE_ATTR_CALLS = frozenset({
    "memory_stats", "stat", "iterdir", "is_file", "is_dir", "exists",
})

#: the sanctioned laundering points: their RESULT is replicated by
#: construction (one allgather + a deterministic reduce on every host),
#: so taint does not flow through them.  ``governor_consensus`` is the
#: governor's documented seam onto the same primitive.
_LAUNDER = frozenset({
    "replicated_decision", "reduce_decision", "governor_consensus",
})

#: calls that issue (or build a program that will issue) collectives —
#: the sinks JG001 protects.  ``replicated_decision`` is deliberately in
#: BOTH sets: as a *value* it launders, but *calling* it under divergent
#: control is itself the deadlock (some hosts join the allgather, some
#: don't).  ``make_train_step``/``make_eval_step`` cover ``Plan.make_*``
#: and the parallel/step.py factories: a host-divergent choice of
#: program is the same hazard one trace later.
_COLLECTIVE_CALLS = frozenset({
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
    "ppermute", "pshuffle", "psum_scatter", "process_allgather",
    "gather_values", "replicated_decision", "governor_consensus",
    "make_train_step", "make_eval_step",
})

#: ``<receiver>.save(...)`` counts as a sink when the receiver looks
#: like a checkpoint manager: a host skipping (or doubling) a
#: checkpoint save desynchronizes the save barrier and the restore set
_CKPT_RECV_RE = re.compile(r"(ckpt|checkpoint|manager|mgr)",
                           re.IGNORECASE)

_SHARD_MAP_NAMES = frozenset({"shard_map"})


def _last_segment(call: ast.Call) -> str | None:
    name = dotted_name(call.func)
    if name:
        return name.rsplit(".", 1)[-1]
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    return None


def _is_source_name(name: str) -> bool:
    if name in _SOURCE_NAMES:
        return True
    return any(name == p.rstrip(".") or name.startswith(p)
               for p in _SOURCE_PREFIXES)


def shard_mapped_names(tree: ast.AST) -> frozenset[str]:
    """Names bound to ``shard_map(...)``-built callables in this module —
    calling one issues that program's collectives, so they join the
    JG001 sink set."""
    names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call):
            f = dotted_name(node.value.func)
            if f and f.rsplit(".", 1)[-1] in _SHARD_MAP_NAMES:
                for t in node.targets:
                    names.update(target_names(t))
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                d = deco.func if isinstance(deco, ast.Call) else deco
                nm = dotted_name(d)
                if nm and nm.rsplit(".", 1)[-1] in _SHARD_MAP_NAMES:
                    names.add(node.name)
    return frozenset(names)


def _expr_source(node: ast.AST, tainted: set[str]) -> str | None:
    """The host-divergent source feeding this expression, or None.
    Descent stops at laundering calls — their result is replicated."""
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Call):
            name = dotted_name(n.func)
            last = _last_segment(n)
            if last in _LAUNDER:
                continue  # replicated by contract — clean, don't descend
            if name and _is_source_name(name):
                return name
            if last in _SOURCE_ATTR_CALLS:
                return f".{last}()"
            stack.extend(ast.iter_child_nodes(n))
        elif isinstance(n, ast.Name):
            if n.id in tainted:
                return n.id
        elif isinstance(n, ast.Attribute):
            d = dotted_name(n)
            if d is not None:
                if _is_source_name(d) or d in tainted:
                    return d
                # x.attr with x (or a dotted prefix) tainted
                parts = d.split(".")
                for k in range(1, len(parts)):
                    if ".".join(parts[:k]) in tainted:
                        return d
            else:
                stack.append(n.value)
        elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            continue  # defining is not evaluating
        else:
            stack.extend(ast.iter_child_nodes(n))
    return None


def _terminates(body: list[ast.stmt]) -> bool:
    """Does this branch unconditionally leave the enclosing block?  A
    host-divergent ``if tainted: return`` gates everything AFTER the if
    just as surely as nesting it would."""
    return bool(body) and isinstance(
        body[-1], (ast.Return, ast.Raise, ast.Break, ast.Continue))


class _DivergenceScanner:
    """One flow-sensitive walk per function scope (and per module body):
    statements in order, assignments move taint, laundering rebinds
    clear it, and collective-issuing calls under an active divergent
    gate are findings."""

    def __init__(self, path: str, shard_names: frozenset[str]):
        self.path = path
        self.shard_names = shard_names
        self.findings: list[Finding] = []
        self._seen: set[int] = set()  # id(call node) — one finding each

    # -- sinks ---------------------------------------------------------
    def _collective_callee(self, call: ast.Call) -> str | None:
        last = _last_segment(call)
        if last in _COLLECTIVE_CALLS or last in self.shard_names:
            return dotted_name(call.func) or last
        if last == "save" and isinstance(call.func, ast.Attribute):
            recv = dotted_name(call.func.value) or ""
            if _CKPT_RECV_RE.search(recv):
                return f"{recv}.save"
        return None

    def _scan_sinks(self, node: ast.AST, gates: list) -> None:
        if not gates:
            return
        for n in ast.walk(node):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # a nested def's body runs at call time
            if not isinstance(n, ast.Call) or id(n) in self._seen:
                continue
            callee = self._collective_callee(n)
            if callee is None:
                continue
            self._seen.add(id(n))
            gate_node, source = gates[-1]
            self.findings.append(Finding(
                "JG001",
                f"collective-issuing call `{callee}` under "
                f"host-divergent control (gated at line "
                f"{gate_node.lineno} by {source}) — hosts taking "
                "different branches deadlock at the first mismatched "
                "collective; route the decision through "
                "parallel/consensus.replicated_decision",
                self.path, getattr(n, "lineno", gate_node.lineno),
                getattr(n, "col_offset", 0)))

    # -- statements ----------------------------------------------------
    def run_block(self, stmts: list[ast.stmt], tainted: set[str],
                  gates: list) -> None:
        gates = list(gates)
        for s in stmts:
            extra = self._stmt(s, tainted, gates)
            if extra is not None:
                # a divergent early exit: the REST of this block only
                # runs on hosts that didn't take it
                gates.append(extra)

    def _assign(self, targets, value_src: str | None,
                tainted: set[str]) -> None:
        for t in targets:
            for name in target_names(t):
                if value_src is None:
                    tainted.discard(name)
                else:
                    tainted.add(name)

    def _stmt(self, s: ast.stmt, tainted: set[str], gates: list):
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in s.decorator_list:
                self._scan_sinks(deco, gates)
            self.run_block(s.body, set(), [])  # fresh scope, runs later
            return None
        if isinstance(s, ast.ClassDef):
            self.run_block(s.body, set(), gates)
            return None
        if isinstance(s, ast.If):
            self._scan_sinks(s.test, gates)
            src = _expr_source(s.test, tainted)
            sub = gates + [(s, src)] if src else gates
            t_body, t_else = set(tainted), set(tainted)
            for b, t in ((s.body, t_body), (s.orelse, t_else)):
                self.run_block(b, t, sub)
            tainted |= t_body | t_else
            if src and (_terminates(s.body) or _terminates(s.orelse)):
                return (s, src)
            return None
        if isinstance(s, ast.While):
            self._scan_sinks(s.test, gates)
            src = _expr_source(s.test, tainted)
            sub = gates + [(s, src)] if src else gates
            for _ in range(2):  # taint fixed point across iterations
                self.run_block(s.body, tainted, sub)
            self.run_block(s.orelse, tainted, gates)
            return None
        if isinstance(s, ast.For):
            self._scan_sinks(s.iter, gates)
            src = _expr_source(s.iter, tainted)
            sub = gates + [(s, src)] if src else gates
            if src:  # divergent trip count/order taints the loop var
                self._assign([s.target], src, tainted)
            for _ in range(2):
                self.run_block(s.body, tainted, sub)
            self.run_block(s.orelse, tainted, gates)
            return None
        if isinstance(s, ast.Try):
            self.run_block(s.body, tainted, gates)
            for h in s.handlers:
                self.run_block(h.body, tainted, gates)
            self.run_block(s.orelse, tainted, gates)
            self.run_block(s.finalbody, tainted, gates)
            return None
        if isinstance(s, ast.With):
            for item in s.items:
                self._scan_sinks(item.context_expr, gates)
                src = _expr_source(item.context_expr, tainted)
                if item.optional_vars is not None:
                    self._assign([item.optional_vars], src, tainted)
            self.run_block(s.body, tainted, gates)
            return None
        # leaf statements: scan for gated sinks, then move taint
        self._scan_sinks(s, gates)
        if isinstance(s, ast.Assign):
            self._assign(s.targets, _expr_source(s.value, tainted),
                         tainted)
        elif isinstance(s, ast.AnnAssign) and s.value is not None:
            self._assign([s.target], _expr_source(s.value, tainted),
                         tainted)
        elif isinstance(s, ast.AugAssign):
            src = _expr_source(s.value, tainted)
            if src is not None:  # += never un-taints
                self._assign([s.target], src, tainted)
        return None


def find_host_divergence(tree: ast.AST, path: str) -> list[Finding]:
    """JG001 over one parsed module."""
    scanner = _DivergenceScanner(path, shard_mapped_names(tree))
    scanner.run_block(tree.body, set(), [])
    return scanner.findings


# --------------------------------------------------------------- JG002 model

def rle(seq: list[str]) -> list[str]:
    """Run-length encode an op sequence: ``["psum","psum","ag"] ->
    ["psum*2","ag"]`` — schedule pins stay reviewable at train-step
    scale (hundreds of collectives, dozens of runs)."""
    out: list[str] = []
    for op, group in itertools.groupby(seq):
        n = sum(1 for _ in group)
        out.append(op if n == 1 else f"{op}*{n}")
    return out


def rle_expand(seq: list[str]) -> list[str]:
    out: list[str] = []
    for item in seq:
        if "*" in item:
            op, n = item.rsplit("*", 1)
            out.extend([op] * int(n))
        else:
            out.append(item)
    return out


def _first_mismatch(a: list[str], b: list[str]) -> str:
    ea, eb = rle_expand(a), rle_expand(b)
    for i, (x, y) in enumerate(zip(ea, eb)):
        if x != y:
            return f"position {i}: {x} != {y}"
    return f"length {len(ea)} != {len(eb)}"


def schedule_divergence(schedules: dict[str, dict[str, list[str]]],
                        declared_divergent: list | tuple = ()
                        ) -> list[Finding]:
    """JG002: pairwise over programs sharing a mesh axis, the ordered
    per-axis collective sequences must match — or the pair must be
    DECLARED divergent (the plan ladder's rungs, whose single-rung-per-
    job invariant the consensus vote enforces at runtime).

    ``schedules``: ``{program: {axis: [rle ops...]}}`` as
    :func:`ir.mesh_axis_collective_schedule` extracts them.
    """
    declared = {frozenset(p) for p in declared_divergent}
    findings: list[Finding] = []
    for a, b in itertools.combinations(sorted(schedules), 2):
        if frozenset((a, b)) in declared:
            continue
        for ax in sorted(set(schedules[a]) & set(schedules[b])):
            if schedules[a][ax] != schedules[b][ax]:
                findings.append(Finding(
                    "JG002",
                    f"schedule divergence between {a} and {b} on mesh "
                    f"axis {ax!r} ({_first_mismatch(schedules[a][ax], schedules[b][ax])}) "
                    "— hosts running these programs as alternates "
                    "deadlock at that collective; pick one program per "
                    "job via replicated_decision and declare the pair "
                    "divergent in the guard schedule contract",
                    f"<{a}|{b}>", 0, 0))
                break  # one finding per pair — the rest is detail
    return findings


def stale_divergence_declarations(
        schedules: dict[str, dict[str, list[str]]],
        declared_divergent: list | tuple) -> list[str]:
    """Declared-divergent pairs that no longer diverge (or whose
    programs vanished) — a stale allowlist entry is itself a failure,
    same contract as the lint suppressions (``jaxlint --stats``)."""
    stale: list[str] = []
    for pair in declared_divergent:
        a, b = sorted(pair)
        if a not in schedules or b not in schedules:
            stale.append(f"declared-divergent pair ({a}, {b}) names "
                         "unknown program(s) — delete the declaration")
            continue
        shared = set(schedules[a]) & set(schedules[b])
        if all(schedules[a][ax] == schedules[b][ax] for ax in shared):
            stale.append(
                f"declared-divergent pair ({a}, {b}) is now "
                "lockstep-identical on every shared axis — the "
                "declaration is dead, delete it")
    return stale
