"""jaxrace driver: the fourth static-analysis layer — host concurrency.

The first three layers watch the *device* program (jaxlint per-file AST,
jaxguard cross-statement dataflow + cross-program schedules, jaxaudit
compiled IR).  None of them see the 20+ modules of host-side threading
that feed those programs: serve worker/swap/session threads, bounded
prefetch queues, supervisor children, signal handlers.  Every
concurrency bug shipped so far (the PR 6 lane-reservation and
gc-vs-queued races, the PR 10 poll-vs-notify prefetch latency) was
found by hand review.  jaxrace is that review, mechanized, in the same
idiom as the other layers: rules + a checked-in contract + seeded-hazard
tests + one lint gate.

Rules:

====== ========================= ==========================================
JR000  meta                      syntax error / malformed ``# jaxrace:``
                                 directive / dangling ``guarded-by``
JR001  guarded-by discipline     a mutable attribute reachable from more
                                 than one thread root accessed without
                                 its declared (or majority-inferred) lock
JR002  lock-order inversion      the with-lock acquisition graph has a
                                 cycle (potential deadlock), or a
                                 non-reentrant lock is re-acquired
JR003  signal-handler safety     code reachable from a registered signal
                                 handler takes a lock, blocks, or calls
                                 into the (lock-taking) metrics registry
JR004  blocking-call-under-lock  unbounded ``queue.get/put``, ``join()``,
                                 ``sleep``, ``device_get`` or file/network
                                 I/O while holding a lock
====== ========================= ==========================================

Guard declarations ride the suppression comment grammar::

    self._active = 0  # jaxrace: guarded-by=self._lock

Declared guards are authoritative — EVERY access outside ``__init__``
without the lock held is JR001.  Without a declaration, a guard is
inferred by majority use (>= 2 locked accesses, strictly more locked
than bare) — the analyzer learns the discipline a class already follows
and flags the stragglers.  Suppressions use the shared grammar
(``# jaxrace: disable=JR004  -- rationale``); ``jaxlint --stats``
polices them for staleness alongside the other tools'.

The effective guard map + the lock-order edge list are pinned in
``tests/contracts/threads.json`` (contract kind ``"threads"`` — host
analysis is topology-independent, so unlike jaxaudit pins it carries no
platform key).  ``jaxrace check`` fails on findings OR pin drift;
``jaxrace update`` regenerates after a reviewed change.  The runtime
complement is :mod:`threadsan` (``DPTPU_THREADSAN=1``): it wraps the
pinned locks and instruments writes to the pinned attributes so the
existing under-load serve/swap tests dynamically witness the static map.

Everything here is stdlib-only and import-light: the gate runs pre-jax.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import sys
import tokenize

from .core import (
    Finding,
    dotted_name,
    iter_python_files,
    parse_suppressions,
    walk_with_parents,
)

META_CODE = "JR000"

#: code -> (name, summary) — all four are AST-side (no compile half)
RACE_RULES = {
    "JR001": ("guarded-by-discipline",
              "mutable attribute reachable from >1 thread root accessed "
              "without its declared/inferred lock held — hold the lock, "
              "or waive with a rationale if the access is provably "
              "single-threaded or GIL-atomic by design"),
    "JR002": ("lock-order-inversion",
              "the with-lock acquisition graph has a cycle (two threads "
              "taking the same locks in opposite orders deadlock), or a "
              "non-reentrant Lock is re-acquired on one path"),
    "JR003": ("signal-handler-safety",
              "code reachable from a registered signal handler takes a "
              "lock, blocks, or calls the metrics registry — a handler "
              "interrupts arbitrary bytecode, possibly while that very "
              "lock is held; mirror state from normal context instead "
              "(the PreemptionGuard idiom)"),
    "JR004": ("blocking-call-under-lock",
              "unbounded blocking call (queue get/put or wait/join/"
              "result without timeout, sleep, device_get, file or "
              "network I/O) while holding a lock — every other user of "
              "that lock inherits the stall; pass timeout= or move the "
              "call outside the critical section"),
}

RACE_CODES = frozenset(RACE_RULES) | {META_CODE}

#: the checked-in concurrency contract (kind "threads", no platform key)
THREADS_CONTRACT_FILE = "threads.json"

_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
    "Lock", "RLock", "Condition",
}
#: ctors whose lock may be re-acquired by the owning thread (Condition's
#: default underlying lock is an RLock)
_REENTRANT_CTORS = {
    "threading.RLock", "RLock", "threading.Condition", "Condition",
}

#: matched against comment TOKENS only (tokenize), so no ``#`` anchor —
#: the directive may follow prose in the same comment
_GUARDED_RE = re.compile(
    r"jaxrace:\s*guarded-by\s*=\s*(?:self\.)?([A-Za-z_]\w*)")

#: receiver-name hint for queue-shaped .put targets (out_q, self._q,
#: work_queue, ...) — keeps dict/cache .put lookalikes out of JR004
_QUEUEISH_RE = re.compile(r"(?:^|_)q(?:ueue)?s?$|queue", re.IGNORECASE)

#: thread roots every class gets for free: context-manager / iterator /
#: callable protocol entries are called by foreign code like any public
#: method
_PROTOCOL_ROOTS = {"__enter__", "__exit__", "__call__", "__iter__",
                   "__next__"}


# ---------------------------------------------------------------- the model

class _Lock:
    """One mutual-exclusion primitive: stable identity + short label."""

    __slots__ = ("ident", "label", "reentrant")

    def __init__(self, ident: str, label: str, reentrant: bool):
        self.ident, self.label, self.reentrant = ident, label, reentrant


class _Method:
    """Flow facts for one function body (methods and module functions).

    ``held`` sets recorded here are the LOCAL half only — locks taken
    inside this body.  Entry locks (what callers already hold, the
    ``*_locked`` convention) are solved by fixpoint afterwards and
    unioned in at judgement time.
    """

    __slots__ = ("node", "accesses", "calls", "acquires", "blocking")

    def __init__(self, node):
        self.node = node
        #: (attr, node, is_write, frozenset(local held))
        self.accesses: list = []
        #: (callee name, frozenset(local held), node) — self.m() only
        self.calls: list = []
        #: (lock ident, frozenset(local held before), node)
        self.acquires: list = []
        #: (reason, node, frozenset(local held)) — judged after fixpoint
        self.blocking: list = []


class _Class:
    __slots__ = ("name", "key", "node", "locks", "methods", "spawns",
                 "declared", "declared_nodes", "concurrent")

    def __init__(self, name: str, key: str, node):
        self.name, self.key, self.node = name, key, node
        self.locks: dict[str, _Lock] = {}   # attr -> lock
        self.methods: dict[str, _Method] = {}
        self.spawns: set[str] = set()       # method names used as targets
        self.declared: dict[str, str] = {}  # attr -> guarding lock attr
        self.declared_nodes: dict[str, ast.AST] = {}
        self.concurrent = False             # any Thread/executor spawn seen


def _comment_lines(src: str) -> dict[int, str]:
    """lineno -> comment text, via the tokenizer — a ``guarded-by``
    inside a docstring or string literal is prose, not a declaration."""
    try:
        tokens = tokenize.generate_tokens(io.StringIO(src).readline)
        return {t.start[0]: t.string for t in tokens
                if t.type == tokenize.COMMENT}
    except (tokenize.TokenError, SyntaxError, IndentationError):
        return {}


def _rel_path(path: str) -> str:
    """Package-relative form for stable contract keys: everything from
    the ``distributedpytorch_tpu/`` component on; bare basename for
    sources outside the package (test fixtures)."""
    p = path.replace(os.sep, "/")
    idx = p.rfind("distributedpytorch_tpu/")
    return p[idx:] if idx >= 0 else os.path.basename(p)


def _class_key(path: str, cls: str) -> str:
    return f"{_rel_path(path)}:{cls}"


def _ctor_of(node) -> str | None:
    if isinstance(node, ast.Call):
        return dotted_name(node.func)
    return None


def _spawn_target(call: ast.Call) -> ast.AST | None:
    """The callable handed to a thread-spawning API: ``threading.Thread
    (target=...)``, ``threading.Timer(t, fn)``, ``executor.submit(fn)``.
    """
    fn = dotted_name(call.func)
    if fn in ("threading.Thread", "Thread"):
        for kw in call.keywords:
            if kw.arg == "target":
                return kw.value
    elif fn in ("threading.Timer", "Timer") and len(call.args) >= 2:
        return call.args[1]
    elif isinstance(call.func, ast.Attribute) \
            and call.func.attr == "submit" and call.args:
        return call.args[0]
    return None


# ----------------------------------------------------------- the flow walk

class _FlowWalker:
    """One function body: sequential held-lock tracking.

    ``with lock:`` scopes exactly; ``lock.acquire()``/``.release()``
    expression statements toggle for the remainder of the block (the
    acquire-then-try/finally-release idiom); branch bodies get copies so
    a conditional acquire never leaks past its branch.
    """

    def __init__(self, resolve, sink: _Method, class_methods: set[str]):
        self._resolve = resolve          # expr -> _Lock | None
        self._sink = sink
        self._class_methods = class_methods

    def run(self, body: list) -> None:
        self._stmts(body, set())

    # ---- statements
    def _stmts(self, stmts, held: set) -> None:
        for st in stmts:
            self._stmt(st, held)

    def _stmt(self, st, held: set) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: runs in its own (possibly other-thread) context
            self._stmts(st.body, set())
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            entered = []
            for item in st.items:
                self._expr(item.context_expr, held)
                lock = self._resolve(item.context_expr)
                if lock is not None:
                    self._sink.acquires.append(
                        (lock.ident, frozenset(held), item.context_expr))
                    held.add(lock.ident)
                    entered.append(lock.ident)
            self._stmts(st.body, held)
            for ident in entered:
                held.discard(ident)
        elif isinstance(st, ast.Try):
            self._stmts(st.body, held)
            for h in st.handlers:
                self._stmts(h.body, set(held))
            self._stmts(st.orelse, set(held))
            self._stmts(st.finalbody, held)
        elif isinstance(st, ast.If):
            self._expr(st.test, held)
            self._stmts(st.body, set(held))
            self._stmts(st.orelse, set(held))
        elif isinstance(st, ast.While):
            self._expr(st.test, held)
            self._stmts(st.body, set(held))
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            self._expr(st.iter, held)
            self._expr(st.target, held)
            self._stmts(st.body, set(held))
            self._stmts(st.orelse, set(held))
        elif isinstance(st, ast.Expr):
            call = st.value
            if isinstance(call, ast.Call) \
                    and isinstance(call.func, ast.Attribute) \
                    and call.func.attr in ("acquire", "release"):
                lock = self._resolve(call.func.value)
                if lock is not None:
                    self._expr(call, held)
                    if call.func.attr == "acquire":
                        self._sink.acquires.append(
                            (lock.ident, frozenset(held), call))
                        held.add(lock.ident)
                    else:
                        held.discard(lock.ident)
                    return
            self._expr(st.value, held)
        else:
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.stmt):
                    self._stmt(child, held)
                elif isinstance(child, ast.expr):
                    self._expr(child, held)

    # ---- expressions
    def _expr(self, e, held: set) -> None:
        if e is None:
            return
        snap = frozenset(held)
        for node in ast.walk(e):
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "self":
                self._sink.accesses.append(
                    (node.attr, node,
                     isinstance(node.ctx, (ast.Store, ast.Del)), snap))
            elif isinstance(node, ast.Call):
                fn = dotted_name(node.func)
                if fn and fn.startswith("self.") and "." not in fn[5:] \
                        and fn[5:] in self._class_methods:
                    self._sink.calls.append((fn[5:], snap, node))
                target = _spawn_target(node)
                if target is not None:
                    d = dotted_name(target)
                    if d and d.startswith("self."):
                        self._sink.calls.append((d[5:], snap, node))
                reason = _blocking_reason(node)
                if reason is not None:
                    recv_lock = None
                    if isinstance(node.func, ast.Attribute):
                        recv_lock = self._resolve(node.func.value)
                    # Condition.wait releases the lock it IS — holding
                    # only that one lock while waiting on it is the
                    # sanctioned idiom, not a stall
                    if not (recv_lock is not None
                            and node.func.attr in ("wait", "acquire")
                            and snap <= {recv_lock.ident}):
                        self._sink.blocking.append((reason, node, snap))


def _blocking_reason(call: ast.Call) -> str | None:
    """Why this call can block unboundedly, or None."""
    fn = dotted_name(call.func)
    if fn in ("time.sleep",):
        return "time.sleep"
    if fn in ("jax.device_get", "device_get"):
        return "device readback (device_get)"
    if fn == "open":
        return "file I/O (open)"
    if fn and (fn.startswith("requests.") or fn.startswith("urllib.")
               or fn.startswith("socket.")):
        return f"network I/O ({fn})"
    if not isinstance(call.func, ast.Attribute):
        return None
    meth = call.func.attr
    kwnames = {k.arg for k in call.keywords}
    has_timeout = "timeout" in kwnames or "block" in kwnames \
        or "blocking" in kwnames
    recv = dotted_name(call.func.value) or ""
    rname = recv.split(".")[-1]
    if meth == "get" and not call.args and not call.keywords:
        return "queue .get() without timeout"
    if meth == "put" and not has_timeout and call.args \
            and _QUEUEISH_RE.search(rname):
        return "queue .put() without timeout"
    if meth in ("join", "result", "wait") and not call.args \
            and not has_timeout:
        return f".{meth}() without timeout"
    if meth == "acquire" and not has_timeout \
            and not (call.args
                     and isinstance(call.args[0], ast.Constant)
                     and call.args[0].value is False):
        return ".acquire() without timeout"
    return None


# --------------------------------------------------------- model extraction

def _collect_local_locks(fn_node, owner: str) -> dict[str, _Lock]:
    """``room = threading.Condition()``-style locals anywhere in the
    function subtree (closures share the enclosing function's locals)."""
    out: dict[str, _Lock] = {}
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            ctor = _ctor_of(node.value)
            if ctor in _LOCK_CTORS:
                name = node.targets[0].id
                out[name] = _Lock(f"{owner}.{name}", name,
                                  ctor in _REENTRANT_CTORS)
    return out


def _make_resolver(cls: _Class | None, local_locks: dict,
                   module_locks: dict):
    """expr -> _Lock for ``self.X`` (class locks), bare names (function
    locals first, then module-level locks)."""

    def resolve(expr) -> _Lock | None:
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and cls is not None:
            return cls.locks.get(expr.attr)
        if isinstance(expr, ast.Name):
            return local_locks.get(expr.id) or module_locks.get(expr.id)
        return None

    return resolve


def _extract_class(node: ast.ClassDef, path: str,
                   comments: dict[int, str],
                   module_locks: dict, meta: list[Finding]) -> _Class:
    cls = _Class(node.name, _class_key(path, node.name), node)
    # pass 1: lock attrs + spawn targets (anywhere in the class body)
    for sub in ast.walk(node):
        if isinstance(sub, ast.Assign):
            ctor = _ctor_of(sub.value)
            if ctor in _LOCK_CTORS:
                for t in sub.targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        cls.locks[t.attr] = _Lock(
                            f"{cls.key}.{t.attr}", t.attr,
                            ctor in _REENTRANT_CTORS)
        elif isinstance(sub, ast.Call):
            target = _spawn_target(sub)
            if target is not None:
                cls.concurrent = True
                d = dotted_name(target)
                if d and d.startswith("self.") and "." not in d[5:]:
                    cls.spawns.add(d[5:])
    # pass 2: guarded-by declarations (on self.X assignment lines)
    for sub in ast.walk(node):
        if not (isinstance(sub, ast.Attribute)
                and isinstance(sub.value, ast.Name)
                and sub.value.id == "self"
                and isinstance(sub.ctx, ast.Store)):
            continue
        m = _GUARDED_RE.search(comments.get(sub.lineno, ""))
        if m is None:
            continue
        lock_attr = m.group(1)
        if lock_attr not in cls.locks:
            meta.append(Finding(
                META_CODE,
                f"guarded-by names '{lock_attr}', which is not a lock "
                f"attribute of {cls.name} (locks: "
                f"{', '.join(sorted(cls.locks)) or 'none'})",
                path, sub.lineno, sub.col_offset))
            continue
        cls.declared[sub.attr] = lock_attr
        cls.declared_nodes[sub.attr] = sub
    # pass 3: flow walk per method (direct children only)
    method_names = {b.name for b in node.body
                    if isinstance(b, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))}
    for b in node.body:
        if not isinstance(b, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        mi = _Method(b)
        locals_ = _collect_local_locks(b, f"{cls.key}.{b.name}")
        walker = _FlowWalker(_make_resolver(cls, locals_, module_locks),
                             mi, method_names)
        walker.run(b.body)
        cls.methods[b.name] = mi
    return cls


def _dangling_guarded_by(comments: dict[int, str], path: str,
                         claimed: set[int]) -> list[Finding]:
    """A ``guarded-by`` comment on a line with no ``self.X = ...`` store
    declares nothing — loud, like an unknown code in a disable."""
    out: list[Finding] = []
    for i in sorted(comments):
        if _GUARDED_RE.search(comments[i]) and i not in claimed:
            out.append(Finding(
                META_CODE,
                "dangling guarded-by: no attribute assignment on this "
                "line to attach the declaration to",
                path, i, 0))
    return out


# --------------------------------------------------- per-class judgements

def _roots_of(cls: _Class) -> set[str]:
    # *_locked methods are the repo's caller-holds-the-lock convention —
    # public spelling or not, they are helpers, never thread entries
    roots = {m for m in cls.methods
             if (not m.startswith("_") or m in _PROTOCOL_ROOTS)
             and not m.endswith("_locked")}
    roots |= cls.spawns & set(cls.methods)
    roots.discard("__init__")
    return roots


def _entry_locks(cls: _Class, roots: set[str]) -> dict[str, frozenset]:
    """Must-hold lock set at entry of each method: roots enter bare;
    private helpers (the ``*_locked`` convention) inherit the
    intersection over every intra-class call site.  Descending fixpoint;
    a never-called helper ends bare."""
    entry: dict[str, frozenset | None] = {
        m: (frozenset() if m in roots else None) for m in cls.methods}
    for _ in range(len(cls.methods) + 2):
        changed = False
        for m in cls.methods:
            if m in roots:
                continue
            sites = []
            for caller, mi in cls.methods.items():
                if entry[caller] is None:
                    continue
                for callee, held, _node in mi.calls:
                    if callee == m:
                        sites.append(entry[caller] | held)
            new = frozenset.intersection(*sites) if sites else None
            if new != entry[m]:
                entry[m] = new
                changed = True
        if not changed:
            break
    return {m: (e if e is not None else frozenset())
            for m, e in entry.items()}


def _reachable_roots(cls: _Class, roots: set[str]) -> dict[str, set]:
    reach: dict[str, set] = {m: set() for m in cls.methods}
    for root in roots:
        seen: set[str] = set()
        stack = [root]
        while stack:
            m = stack.pop()
            if m in seen or m not in cls.methods:
                continue
            seen.add(m)
            reach[m].add(root)
            stack.extend(c for c, _h, _n in cls.methods[m].calls)
    return reach


def _judge_class(cls: _Class, path: str
                 ) -> tuple[list[Finding], dict[str, str], list]:
    """JR001 findings + the class's effective guard map + its
    acquisition edges ``(from_ident, to_ident, node)``."""
    findings: list[Finding] = []
    roots = _roots_of(cls)
    entry = _entry_locks(cls, roots)
    reach = _reachable_roots(cls, roots)

    # lock-order edges: direct nesting, entry-lock nesting, and one
    # level of call-site propagation (holding A, call m that takes B)
    edges: list = []
    for m, mi in cls.methods.items():
        for ident, held_before, node in mi.acquires:
            for h in (entry[m] | held_before):
                edges.append((h, ident, node))
            if not (entry[m] | held_before) and ident in held_before:
                pass  # unreachable; kept for clarity
        for callee, held, node in mi.calls:
            full = entry[m] | held
            if not full or callee not in cls.methods:
                continue
            for ident, _hb, _n in cls.methods[callee].acquires:
                for h in full:
                    edges.append((h, ident, node))

    # JR001
    by_attr: dict[str, list] = {}
    for m, mi in cls.methods.items():
        if m == "__init__":
            continue
        for attr, node, write, held in mi.accesses:
            by_attr.setdefault(attr, []).append((m, node, write, held))

    guards: dict[str, str] = dict(cls.declared)
    own_lock_idents = {lk.ident: a for a, lk in cls.locks.items()}

    for attr, accs in sorted(by_attr.items()):
        if attr in cls.locks:
            continue
        declared = cls.declared.get(attr)
        if declared is not None:
            guard = cls.locks[declared]
            judged = accs
        else:
            if not (cls.locks and (cls.concurrent or cls.spawns
                                   or len(cls.locks) > 0)):
                continue
            live = [(m, n, w, h) for m, n, w, h in accs if reach[m]]
            if not live or not any(w for _m, _n, w, _h in live):
                continue
            roots_union = set()
            for m, _n, _w, _h in live:
                roots_union |= reach[m]
            if len(roots_union) < 2:
                continue
            counts: dict[str, int] = {}
            for m, _n, _w, h in live:
                for ident in (entry[m] | h) & set(own_lock_idents):
                    counts[ident] = counts.get(ident, 0) + 1
            if not counts:
                continue
            best = max(sorted(counts), key=lambda k: counts[k])
            locked = counts[best]
            bare = sum(1 for m, _n, _w, h in live
                       if best not in (entry[m] | h))
            if locked < 2 or locked <= bare:
                continue
            guard = cls.locks[own_lock_idents[best]]
            guards[attr] = own_lock_idents[best]
            judged = live
        for m, node, write, held in judged:
            if guard.ident in (entry[m] | held):
                continue
            mode = "declared" if declared else "majority-inferred"
            rooted = sorted(reach[m]) or [m]
            findings.append(Finding(
                "JR001",
                f"'{attr}' ({cls.name}) "
                f"{'written' if write else 'read'} without "
                f"'{guard.label}' held ({mode} guard) — reachable from "
                f"thread root(s): {', '.join(rooted[:4])}",
                path, node.lineno, node.col_offset))

    # JR004 (held = entry | local at the recorded site)
    for m, mi in cls.methods.items():
        for reason, node, held in mi.blocking:
            full = entry[m] | held
            if full:
                labels = sorted(own_lock_idents.get(i, i.split(".")[-1])
                                for i in full)
                findings.append(Finding(
                    "JR004",
                    f"blocking {reason} while holding "
                    f"{', '.join(repr(x) for x in labels)} "
                    f"(in {cls.name}.{m})",
                    path, node.lineno, node.col_offset))
    return findings, guards, edges


def _judge_function(fn_name: str, mi: _Method, path: str
                    ) -> tuple[list[Finding], list]:
    """Module-level functions: JR004 + lock-order edges only (no
    attributes to guard)."""
    findings: list[Finding] = []
    edges = [(h, ident, node) for ident, held, node in mi.acquires
             for h in held]
    for reason, node, held in mi.blocking:
        if held:
            labels = sorted(i.split(".")[-1] for i in held)
            findings.append(Finding(
                "JR004",
                f"blocking {reason} while holding "
                f"{', '.join(repr(x) for x in labels)} (in {fn_name})",
                path, node.lineno, node.col_offset))
    return findings, edges


# ------------------------------------------------------------ lock ordering

def _order_findings(edges: list, path: str, locks_by_ident: dict
                    ) -> list[Finding]:
    """JR002: cycles in the acquisition graph; self-edges on
    non-reentrant locks are the degenerate (self-deadlock) case."""
    findings: list[Finding] = []
    graph: dict[str, dict[str, ast.AST]] = {}
    for a, b, node in edges:
        if a == b:
            lock = locks_by_ident.get(a)
            if lock is not None and not lock.reentrant:
                findings.append(Finding(
                    "JR002",
                    f"non-reentrant lock '{lock.label}' re-acquired "
                    "while already held — self-deadlock (use RLock or "
                    "restructure)",
                    path, node.lineno, node.col_offset))
            continue
        graph.setdefault(a, {}).setdefault(b, node)

    # DFS cycle detection with canonicalized reporting (one per cycle)
    seen_cycles: set[tuple] = set()
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in
             set(graph) | {b for m in graph.values() for b in m}}
    stack: list[str] = []

    def visit(n: str) -> None:
        color[n] = GRAY
        stack.append(n)
        for nxt, node in sorted(graph.get(n, {}).items()):
            if color[nxt] == GRAY:
                cyc = stack[stack.index(nxt):] + [nxt]
                lo = min(range(len(cyc) - 1), key=lambda i: cyc[i])
                canon = tuple(cyc[lo:-1] + cyc[:lo])
                if canon not in seen_cycles:
                    seen_cycles.add(canon)
                    labels = [c.split(".")[-1] for c in cyc]
                    findings.append(Finding(
                        "JR002",
                        "lock-order inversion: "
                        + " -> ".join(labels)
                        + " — two threads traversing this cycle from "
                        "different entry points deadlock; pick one "
                        "order and pin it",
                        path, node.lineno, node.col_offset))
            elif color[nxt] == WHITE:
                visit(nxt)
        stack.pop()
        color[n] = BLACK

    for n in sorted(color):
        if color[n] == WHITE:
            visit(n)
    return findings


# --------------------------------------------------------- signal handlers

_HANDLER_UNSAFE_CALLS = {
    "time.sleep": "sleeps",
    "open": "performs file I/O",
    "get_registry": "calls the metrics registry (its counters take "
                    "locks — mirror from normal context, the "
                    "PreemptionGuard idiom)",
}


def _signal_findings(tree, path: str, classes: dict[str, _Class],
                     module_defs: dict, module_locks: dict
                     ) -> list[Finding]:
    parents = walk_with_parents(tree)
    findings: list[Finding] = []
    # handlers registered by bare name may be nested defs (serve
    # __main__'s on_signal lives inside main()) — resolve any def that
    # is not a method
    module_defs = dict(module_defs)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and not isinstance(parents.get(node), ast.ClassDef) \
                and node.name not in module_defs:
            module_defs[node.name] = node

    def enclosing_class(node) -> _Class | None:
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return classes.get(cur.name)
            cur = parents.get(cur)
        return None

    def check_body(nodes, handler: str, owner: _Class | None,
                   depth: int, visited: set) -> None:
        resolver = _make_resolver(owner, {}, module_locks)
        callees: list[tuple] = []
        for top in nodes:
            for node in ast.walk(top):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        lock = resolver(item.context_expr)
                        d = dotted_name(item.context_expr) or ""
                        if lock is not None or "lock" in d.lower():
                            findings.append(Finding(
                                "JR003",
                                f"signal path '{handler}' acquires lock "
                                f"'{d or lock.label}' — a handler can "
                                "interrupt the holder and deadlock",
                                path, item.context_expr.lineno,
                                item.context_expr.col_offset))
                elif isinstance(node, ast.Call):
                    fn = dotted_name(node.func) or ""
                    if isinstance(node.func, ast.Attribute) \
                            and node.func.attr == "acquire":
                        nonblocking = any(
                            k.arg == "blocking"
                            and isinstance(k.value, ast.Constant)
                            and k.value.value is False
                            for k in node.keywords) or (
                            node.args
                            and isinstance(node.args[0], ast.Constant)
                            and node.args[0].value is False)
                        if not nonblocking:
                            findings.append(Finding(
                                "JR003",
                                f"signal path '{handler}' calls blocking "
                                ".acquire() — use acquire(blocking="
                                "False) (the TraceCapture idiom) or a "
                                "plain attribute flag",
                                path, node.lineno, node.col_offset))
                    for pat, verb in _HANDLER_UNSAFE_CALLS.items():
                        if fn == pat or fn.endswith("." + pat):
                            findings.append(Finding(
                                "JR003",
                                f"signal path '{handler}' {verb}",
                                path, node.lineno, node.col_offset))
                    if _blocking_reason(node) is not None \
                            and (not isinstance(node.func, ast.Attribute)
                                 or node.func.attr not in ("acquire",)):
                        reason = _blocking_reason(node)
                        if reason not in ("time.sleep",):  # reported above
                            findings.append(Finding(
                                "JR003",
                                f"signal path '{handler}' may block: "
                                f"{reason}",
                                path, node.lineno, node.col_offset))
                    if depth == 0:
                        if fn.startswith("self.") and owner is not None \
                                and fn[5:] in owner.methods:
                            callees.append((fn[5:], owner))
                        elif fn in module_defs:
                            callees.append((fn, None))
        for name, ocls in callees:
            if name in visited:
                continue
            visited.add(name)
            body = (ocls.methods[name].node.body if ocls is not None
                    else module_defs[name].body)
            check_body(body, f"{handler} -> {name}", ocls, 1, visited)

    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and dotted_name(node.func) in ("signal.signal",)
                and len(node.args) >= 2):
            continue
        h = node.args[1]
        d = dotted_name(h)
        if isinstance(h, ast.Lambda):
            owner = enclosing_class(node)
            check_body([h.body], "<lambda>", owner, 0, set())
        elif d and d.startswith("self."):
            owner = enclosing_class(node)
            name = d[5:]
            if owner is not None and name in owner.methods:
                check_body(owner.methods[name].node.body, name, owner,
                           0, {name})
        elif d and d in module_defs:
            check_body(module_defs[d].body, d, None, 0, {d})
    return findings


# -------------------------------------------------------------- file driver

def _analyze_file(src: str, path: str, tree=None
                  ) -> tuple[list[Finding], dict, list]:
    """Raw findings + ``{class_key: {attr: lock_attr}}`` guard map +
    lock-order edges ``(a, b)`` for one file."""
    if tree is None:
        tree = ast.parse(src)
    comments = _comment_lines(src)
    meta: list[Finding] = []

    # module-level locks + defs
    module_locks: dict[str, _Lock] = {}
    module_defs: dict[str, ast.FunctionDef] = {}
    rel = _rel_path(path)
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            ctor = _ctor_of(node.value)
            if ctor in _LOCK_CTORS:
                name = node.targets[0].id
                module_locks[name] = _Lock(
                    f"{rel}:{name}", name, ctor in _REENTRANT_CTORS)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            module_defs[node.name] = node

    classes: dict[str, _Class] = {}
    claimed_lines: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            cls = _extract_class(node, path, comments, module_locks,
                                 meta)
            classes[cls.name] = cls
            claimed_lines |= {n.lineno
                              for n in cls.declared_nodes.values()}
    meta.extend(_dangling_guarded_by(comments, path, claimed_lines))

    findings: list[Finding] = list(meta)
    guards: dict[str, dict[str, str]] = {}
    all_edges: list = []
    locks_by_ident: dict[str, _Lock] = dict(module_locks and {
        lk.ident: lk for lk in module_locks.values()} or {})

    for cls in classes.values():
        for lk in cls.locks.values():
            locks_by_ident[lk.ident] = lk
        f, g, e = _judge_class(cls, path)
        findings.extend(f)
        if g:
            guards[cls.key] = g
        all_edges.extend(e)

    for name, fn_node in module_defs.items():
        mi = _Method(fn_node)
        locals_ = _collect_local_locks(fn_node, f"{rel}:{name}")
        for lk in locals_.values():
            locks_by_ident[lk.ident] = lk
        walker = _FlowWalker(_make_resolver(None, locals_, module_locks),
                             mi, set())
        walker.run(fn_node.body)
        f, e = _judge_function(name, mi, path)
        findings.extend(f)
        all_edges.extend(e)

    findings.extend(_order_findings(all_edges, path, locks_by_ident))
    findings.extend(_signal_findings(tree, path, classes, module_defs,
                                     module_locks))
    edge_pairs = sorted({(a, b) for a, b, _n in all_edges if a != b})
    return findings, guards, edge_pairs


def race_source(src: str, path: str = "<string>", tree=None,
                suppress: bool = True) -> list[Finding]:
    """All four JR rules over one source string.  ``suppress=False``
    ignores ``# jaxrace:`` disables (the raw view
    :func:`core.suppression_report` audits for staleness)."""
    if tree is None:
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            return [Finding(META_CODE, f"syntax error: {e.msg}", path,
                            e.lineno or 1, e.offset or 0)]
    findings, _guards, _edges = _analyze_file(src, path, tree)
    line_dis, file_dis, meta = parse_suppressions(
        src, path, set(RACE_CODES), tool="jaxrace", meta_code=META_CODE)
    findings.extend(meta)
    if not suppress:
        line_dis, file_dis = {}, set()
    findings = [
        f for f in findings
        if f.code not in file_dis
        and f.code not in line_dis.get(f.line, ())
    ]
    return sorted(findings, key=lambda f: (f.line, f.col, f.code))


def race_paths(paths) -> list[Finding]:
    findings: list[Finding] = []
    for f in iter_python_files(paths):
        with open(f, encoding="utf-8") as fh:
            src = fh.read()
        findings.extend(race_source(src, path=f))
    return sorted(findings, key=lambda x: (x.path, x.line, x.col, x.code))


# ------------------------------------------------------------- the contract

def build_thread_model(paths) -> dict:
    """The pinnable model: effective guard map (declared + inferred) per
    class and the package-wide lock-order edge list."""
    guards: dict[str, dict[str, str]] = {}
    edges: set = set()
    for f in iter_python_files(paths):
        with open(f, encoding="utf-8") as fh:
            src = fh.read()
        try:
            tree = ast.parse(src)
        except SyntaxError:
            continue
        _findings, g, e = _analyze_file(src, f, tree)
        guards.update(g)
        edges.update(e)
    return {"guards": {k: dict(sorted(v.items()))
                       for k, v in sorted(guards.items())},
            "lock_order": [list(p) for p in sorted(edges)]}


def threads_contract_path(contracts_dir: str) -> str:
    return os.path.join(contracts_dir, THREADS_CONTRACT_FILE)


def load_thread_pin(contracts_dir: str) -> dict | None:
    path = threads_contract_path(contracts_dir)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def save_thread_model(model: dict, contracts_dir: str) -> str:
    os.makedirs(contracts_dir, exist_ok=True)
    doc = {"kind": "threads", "program": "threads",
           "guards": model["guards"], "lock_order": model["lock_order"]}
    path = threads_contract_path(contracts_dir)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def diff_thread_model(pinned: dict, model: dict) -> list[str]:
    """Pin drift — a guard map or acquisition order changing without a
    reviewed ``jaxrace update`` fails the gate like a stale jaxaudit
    contract."""
    drift: list[str] = []
    want_g = pinned.get("guards") or {}
    have_g = model["guards"]
    for key in sorted(set(want_g) | set(have_g)):
        if key not in have_g:
            drift.append(f"{key}: pinned guard map vanished — run "
                         "`jaxrace update` after review")
        elif key not in want_g:
            drift.append(f"{key}: new guard map "
                         f"{have_g[key]} — not pinned; run "
                         "`jaxrace update` and review")
        elif want_g[key] != have_g[key]:
            drift.append(f"{key}: guard map changed "
                         f"(pinned {want_g[key]}, live {have_g[key]})")
    want_e = {tuple(p) for p in (pinned.get("lock_order") or [])}
    have_e = {tuple(p) for p in model["lock_order"]}
    for a, b in sorted(want_e - have_e):
        drift.append(f"lock-order edge {a} -> {b}: pinned but no longer "
                     "taken")
    for a, b in sorted(have_e - want_e):
        drift.append(f"lock-order edge {a} -> {b}: new nested "
                     "acquisition — not pinned; review for inversions "
                     "against the existing order, then `jaxrace update`")
    return drift


# ------------------------------------------------------------------- the CLI

def run_race_cli(argv: list[str] | None = None) -> int:
    """``jaxrace {audit|check|update|list} [paths...]``.

    * ``audit``  — findings + the live model (informational, exit 0);
    * ``check``  — the gate: findings or ``threads.json`` drift exit 1;
    * ``update`` — regenerate the pin after a REVIEWED change;
    * ``list``   — the rule table.

    AST-only: no jax import, no compile — safe for pre-commit, runs in
    both halves of ``scripts/lint.sh``.
    """
    import argparse

    parser = argparse.ArgumentParser(
        prog="jaxrace",
        description="static host-concurrency analyzer: guarded-by "
                    "discipline, lock ordering, signal safety, blocking-"
                    "under-lock (see docs/DESIGN.md 'Static analysis').")
    parser.add_argument("command",
                        choices=("audit", "check", "update", "list"),
                        help="audit: print findings+model; check: gate "
                             "(exit 1 on findings/drift); update: "
                             "regenerate threads.json; list: rules")
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    parser.add_argument("paths", nargs="*", default=[pkg_dir],
                        help="files or directories (default: the "
                             "package)")
    parser.add_argument("--contracts-dir", default=None,
                        help="contract directory (default: the repo's "
                             "tests/contracts)")
    args = parser.parse_intermixed_args(argv)

    if args.command == "list":
        print(f"{META_CODE}  meta: syntax error, malformed/unknown "
              "# jaxrace: directive, dangling guarded-by")
        for code in sorted(RACE_RULES):
            name, summary = RACE_RULES[code]
            print(f"{code}  {name}: {summary}")
        return 0

    from .contracts import default_contracts_dir  # import-light (stdlib)

    contracts_dir = args.contracts_dir or default_contracts_dir()
    try:
        findings = race_paths(args.paths)
        model = build_thread_model(args.paths)
    except (FileNotFoundError, ValueError) as e:
        print(f"jaxrace: error: {e}", file=sys.stderr)
        return 2
    for f in findings:
        print(f.format())

    if args.command == "audit":
        print(json.dumps(model, indent=1, sort_keys=True))
        if findings:
            print(f"jaxrace: {len(findings)} finding(s)",
                  file=sys.stderr)
        return 0

    if args.command == "update":
        path = save_thread_model(model, contracts_dir)
        print(f"wrote {path}")
        return 0

    # check
    pinned = load_thread_pin(contracts_dir)
    if pinned is None:
        drift = [f"no thread pin {THREADS_CONTRACT_FILE} in "
                 f"{contracts_dir} — run `jaxrace update` and review"]
    else:
        drift = diff_thread_model(pinned, model)
    for line in drift:
        print(line)
    if not drift:
        print(f"threads: ok ({len(model['guards'])} guarded class(es), "
              f"{len(model['lock_order'])} lock-order edge(s))")
    if findings or drift:
        print(f"jaxrace: {len(findings)} finding(s), "
              f"{len(drift)} contract failure(s)", file=sys.stderr)
        return 1
    return 0


def main(argv: list[str] | None = None) -> int:
    return run_race_cli(argv)


if __name__ == "__main__":
    sys.exit(main())
