"""``python -m distributedpytorch_tpu.analysis [paths...]`` — jaxlint CLI.

``python -m distributedpytorch_tpu.analysis --ir <command> [...]`` routes
to jaxaudit, the IR-level program auditor (``jaxaudit check`` /
``update`` / ``audit`` / ``list`` — see :mod:`contracts`), and
``--guard <command> [...]`` to jaxguard, the cross-program
SPMD-divergence + donation-safety layer (:mod:`guard`), and
``--race <command> [...]`` to jaxrace, the host-concurrency layer
(:mod:`race` — guarded-by discipline, lock ordering, signal safety).
The split keeps the default linter path import-light (no jax): only
``--ir`` — and ``--guard`` without ``--no-ir`` — touches a backend;
``--race`` never does (host threads are topology-independent).
"""

import sys


def _main() -> int:
    argv = sys.argv[1:]
    if "--race" in argv:
        argv = [a for a in argv if a != "--race"]
        from .race import run_race_cli

        return run_race_cli(argv)
    if "--guard" in argv:
        argv = [a for a in argv if a != "--guard"]
        from .guard import run_guard_cli

        return run_guard_cli(argv)
    if "--ir" in argv:
        argv = [a for a in argv if a != "--ir"]
        from .contracts import main as ir_main

        return ir_main(argv)
    from .core import main

    return main(argv)


if __name__ == "__main__":
    sys.exit(_main())
