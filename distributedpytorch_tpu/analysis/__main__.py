"""``python -m distributedpytorch_tpu.analysis [paths...]`` — jaxlint CLI.

``python -m distributedpytorch_tpu.analysis --ir <command> [...]`` routes
to jaxaudit, the IR-level program auditor (``jaxaudit check`` /
``update`` / ``audit`` / ``list`` — see :mod:`contracts`).  The split
keeps the default linter path import-light (no jax): only ``--ir``
touches a backend.
"""

import sys


def _main() -> int:
    argv = sys.argv[1:]
    if "--ir" in argv:
        argv = [a for a in argv if a != "--ir"]
        from .contracts import main as ir_main

        return ir_main(argv)
    from .core import main

    return main(argv)


if __name__ == "__main__":
    sys.exit(_main())
