"""jaxguard donation-aliasing passes: JG003 use-after-donate + JG004
zero-copy donation hazard.

The two nastiest bugs this codebase ever shipped were the same class:

* PR 5's Orbax-restore **segfault** — restored arrays were donated into
  the first train step while Orbax still held views of their host
  buffers; XLA reused the memory and the next host read walked freed
  pages.  Fixed by re-buffering with ``jnp.copy`` in
  ``CheckpointManager.restore``.
* PR 6's warm-start **NaN** — ``jax.device_put(np.asarray(leaf),
  sharding)`` produced zero-copy host-aliased device buffers on CPU;
  donating them into the step let XLA scribble over the numpy arrays a
  later consumer still read.  Same fix, one ``jnp.copy`` earlier.

Both were runtime symptoms (a segfault, a silent NaN) of a statically
visible pattern: a buffer whose host side is still reachable crosses
into a ``donate_argnums`` position, or a donated binding is read after
the dispatch that consumed it.  This module pins the pattern at the
AST level:

* **JG003** — a binding passed in a donated position and then *read* in
  the same scope without being rebound.  The sanctioned idiom rebinds
  through the call (``state, loss = step(state, batch)``), which this
  pass recognizes and clears.
* **JG004** — a host-numpy-derived value (``np.*`` constructors,
  optionally **through** ``jax.device_put`` — device_put is exactly the
  zero-copy trap, it does NOT launder) flowing into a donated position
  without an interposed ``jnp.copy``/``jnp.array`` (which allocate a
  fresh device buffer and do launder; ``jnp.asarray`` does not — it is
  allowed to alias).

The jaxpr half lives in :func:`declared_donations`: the traced
program's ``args_info`` is the ground truth for *which* arguments are
donated — ``--guard audit`` cross-checks the AST-declared donating
callables against it and the contracts pin the count.

Import-light (stdlib only) at module level, like the rest of the AST
layer; :func:`declared_donations` lazily imports jax.
"""

from __future__ import annotations

import ast

from .core import Finding, dotted_name, target_names

_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit", "pjit.pjit"}
_PARTIAL_NAMES = {"functools.partial", "partial"}

#: factory calls whose RESULT donates by convention (position 0 — the
#: previous state's buffers; parallel/step.py, parallel/plan.py's
#: ``Plan.make_train_step``, parallel/pipeline.py)
DONATING_FACTORIES = {
    "make_train_step": (0,),
    "make_pipeline_step": (0,),
}

#: calls that launder host-alias taint: a fresh device allocation
_COPY_LAUNDER = frozenset({
    "jnp.copy", "jax.numpy.copy", "jnp.array", "jax.numpy.array",
})


def _donate_positions(keywords: list[ast.keyword]) -> tuple[int, ...]:
    """The literal ``donate_argnums`` positions of a jit call, if
    statically readable.  ``(0,) if donate else ()`` (this repo's
    factory idiom) reads its then-branch — the donating configuration
    is the one worth policing."""
    for kw in keywords:
        if kw.arg not in ("donate_argnums", "donate_argnames"):
            continue
        node = kw.value
        if isinstance(node, ast.IfExp):
            node = node.body
        if isinstance(node, ast.Constant) and isinstance(node.value, int):
            return (node.value,)
        if isinstance(node, (ast.Tuple, ast.List)):
            out = []
            for el in node.elts:
                if isinstance(el, ast.Constant) \
                        and isinstance(el.value, int):
                    out.append(el.value)
            return tuple(out)
    return ()


def _jit_donations(call: ast.Call) -> tuple[int, ...]:
    f = dotted_name(call.func)
    if f in _JIT_NAMES:
        return _donate_positions(call.keywords)
    return ()


def donating_callables(tree: ast.AST) -> dict[str, tuple[int, ...]]:
    """``{callable name: donated positions}`` for one module — names
    (incl. dotted ``self.train_step`` attributes) bound to
    ``jax.jit(..., donate_argnums=...)`` results or to the known
    donating factories, plus ``@partial(jax.jit, donate_argnums=...)``
    decorated defs."""
    out: dict[str, tuple[int, ...]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call):
            pos = _jit_donations(node.value)
            if not pos:
                f = dotted_name(node.value.func)
                last = f.rsplit(".", 1)[-1] if f else None
                pos = DONATING_FACTORIES.get(last, ())
            if pos:
                for t in node.targets:
                    for name in target_names(t):
                        out[name] = pos
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                if isinstance(deco, ast.Call) \
                        and dotted_name(deco.func) in _PARTIAL_NAMES \
                        and deco.args \
                        and dotted_name(deco.args[0]) in _JIT_NAMES:
                    pos = _donate_positions(deco.keywords)
                    if pos:
                        out[node.name] = pos
    return out


def _host_taint(node: ast.AST, host: dict[str, str]) -> str | None:
    """The host-memory source aliased by this expression, or None.
    numpy results live in host memory; ``device_put`` *carries* the
    alias (zero-copy placement is the bug class); only a fresh device
    allocation (``jnp.copy``/``jnp.array``) clears it."""
    if isinstance(node, ast.Call):
        f = dotted_name(node.func)
        last = f.rsplit(".", 1)[-1] if f else (
            node.func.attr if isinstance(node.func, ast.Attribute)
            else None)
        if f in _COPY_LAUNDER:
            return None
        if f and (f.startswith("np.") or f.startswith("numpy.")):
            return f
        if last == "device_put":
            return _host_taint(node.args[0], host) if node.args else None
        if last == "asarray" and node.args:
            # np.asarray covered above; jnp.asarray may alias — carry
            return _host_taint(node.args[0], host)
        if f and (f.startswith("jnp.") or f.startswith("jax.numpy.")):
            return None  # fresh device result
        return None  # other calls: unknown provenance, stay quiet
    if isinstance(node, ast.Name):
        return host.get(node.id)
    if isinstance(node, ast.Attribute):
        d = dotted_name(node)
        if d is not None and d in host:
            return host[d]
        return None
    if isinstance(node, (ast.BinOp, ast.UnaryOp, ast.Subscript,
                         ast.Tuple, ast.List, ast.Starred, ast.IfExp)):
        for child in ast.iter_child_nodes(node):
            src = _host_taint(child, host)
            if src is not None:
                return src
    return None


class _DonationScanner:
    """Linear walk of one scope: donating calls kill their donated
    argument names (unless the same statement rebinds them), later
    loads are JG003; host-aliased values reaching a donated position
    are JG004."""

    def __init__(self, path: str, don_map: dict[str, tuple[int, ...]]):
        self.path = path
        self.don_map = don_map
        self.findings: list[Finding] = []

    def run_block(self, stmts: list[ast.stmt],
                  donated: dict[str, tuple], host: dict[str, str]
                  ) -> None:
        for s in stmts:
            self._stmt(s, donated, host)

    # ------------------------------------------------------------------
    def _loads(self, node: ast.AST):
        for n in ast.walk(node):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(n, ast.Name) \
                    and isinstance(n.ctx, ast.Load):
                yield n.id, n
            elif isinstance(n, ast.Attribute) \
                    and isinstance(n.ctx, ast.Load):
                d = dotted_name(n)
                if d is not None:
                    yield d, n

    def _donating_calls(self, node: ast.AST):
        for n in ast.walk(node):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not isinstance(n, ast.Call):
                continue
            callee = dotted_name(n.func)
            if callee is None or callee not in self.don_map:
                continue
            yield callee, n, self.don_map[callee]

    def _leaf(self, s: ast.stmt, donated: dict, host: dict) -> None:
        # (a) reads of already-donated bindings — JG003
        reported: set[str] = set()
        for name, node in self._loads(s):
            if name in donated and name not in reported:
                call_line, callee = donated[name]
                self.findings.append(Finding(
                    "JG003",
                    f"`{name}` was donated to `{callee}` (line "
                    f"{call_line}) and is read afterwards — its buffer "
                    "may already be reused by the program "
                    "(use-after-donate); rebind the result "
                    f"(`{name} = {callee}(...)`) or pass "
                    f"ir.struct_of/jnp.copy instead",
                    self.path, node.lineno, node.col_offset))
                reported.add(name)
                donated.pop(name, None)  # one finding per donation
        # (b) this statement's own donating calls
        new_dead: dict[str, tuple] = {}
        for callee, call, positions in self._donating_calls(s):
            for i in positions:
                if i >= len(call.args):
                    continue
                arg = call.args[i]
                hsrc = _host_taint(arg, host)
                if hsrc is not None:
                    self.findings.append(Finding(
                        "JG004",
                        f"host-backed value ({hsrc}) flows into donated "
                        f"argument {i} of `{callee}` without an "
                        "interposed jnp.copy — donating a zero-copy "
                        "host alias lets XLA scribble over memory the "
                        "host still reads (the Orbax-restore segfault / "
                        "warm-start NaN class); wrap it in jnp.copy()",
                        self.path, call.lineno, call.col_offset))
                name = dotted_name(arg)
                if name is not None:
                    new_dead[name] = (call.lineno, callee)
        # (c) rebinds clear — including the rebind-through-the-call idiom
        targets: list[str] = []
        if isinstance(s, ast.Assign):
            for t in s.targets:
                targets.extend(target_names(t))
        elif isinstance(s, (ast.AnnAssign, ast.AugAssign)):
            targets.extend(target_names(s.target))
        for name in targets:
            new_dead.pop(name, None)
            donated.pop(name, None)
        donated.update(new_dead)
        # (d) host-alias taint moves with assignments
        if isinstance(s, ast.Assign):
            src = _host_taint(s.value, host)
            for t in s.targets:
                for name in target_names(t):
                    if src is None:
                        host.pop(name, None)
                    else:
                        host[name] = src
        elif isinstance(s, ast.AnnAssign) and s.value is not None:
            src = _host_taint(s.value, host)
            for name in target_names(s.target):
                if src is None:
                    host.pop(name, None)
                else:
                    host[name] = src

    def _stmt(self, s: ast.stmt, donated: dict, host: dict) -> None:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.run_block(s.body, {}, {})  # fresh scope
            return
        if isinstance(s, ast.ClassDef):
            self.run_block(s.body, {}, {})
            return
        if isinstance(s, (ast.If, ast.While)):
            self._leaf_expr_only(s.test, donated, host)
            self.run_block(s.body, donated, host)
            self.run_block(s.orelse, donated, host)
            return
        if isinstance(s, ast.For):
            self._leaf_expr_only(s.iter, donated, host)
            for name in target_names(s.target):
                donated.pop(name, None)
                host.pop(name, None)
            for _ in range(2):  # loop-carried donations surface pass 2
                self.run_block(s.body, donated, host)
            self.run_block(s.orelse, donated, host)
            return
        if isinstance(s, ast.Try):
            self.run_block(s.body, donated, host)
            for h in s.handlers:
                self.run_block(h.body, donated, host)
            self.run_block(s.orelse, donated, host)
            self.run_block(s.finalbody, donated, host)
            return
        if isinstance(s, ast.With):
            for item in s.items:
                self._leaf_expr_only(item.context_expr, donated, host)
                if item.optional_vars is not None:
                    for name in target_names(item.optional_vars):
                        donated.pop(name, None)
                        host.pop(name, None)
            self.run_block(s.body, donated, host)
            return
        self._leaf(s, donated, host)

    def _leaf_expr_only(self, expr: ast.AST, donated: dict,
                        host: dict) -> None:
        """Header expressions (if/while tests, for iters): reads and
        donating calls count, but there are no assignment targets."""
        holder = ast.Expr(value=expr)
        ast.copy_location(holder, expr)
        self._leaf(holder, donated, host)


def find_donation_hazards(tree: ast.AST, path: str) -> list[Finding]:
    """JG003 + JG004 over one parsed module."""
    don_map = donating_callables(tree)
    scanner = _DonationScanner(path, don_map)
    # module body, then every function scope (its own linear story)
    scanner.run_block(tree.body, {}, {})
    return scanner.findings


def declared_donations(fn, args: tuple) -> int:
    """The jaxpr-side ground truth: how many arguments the traced
    program actually declares donated (``args_info``) — what the AST
    passes *infer*, the trace *knows*.  Shares the process-wide lowering
    cache; raises whatever trace raises."""
    import jax

    from ..telemetry.lowering import lower_cached

    traced = lower_cached(fn, *args).traced
    if traced is None:
        return 0
    return sum(1 for leaf in jax.tree.leaves(traced.args_info)
               if getattr(leaf, "donated", False))
