"""jaxlint driver: rule registry, jit-body detection, suppressions, CLI.

Import-light on purpose (stdlib only — no jax/numpy): the linter must run
in CI containers, pre-commit hooks, and editors without initializing a
backend.  Rules live in :mod:`rules`; this module owns everything they
share — the per-file analysis context (AST, parents, which functions are
jit-traced, the allowed sharding axes) and the suppression grammar.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import sys
import tokenize
from typing import Callable, Iterable, Iterator

#: codes the suppression parser accepts beyond registered rules
META_CODE = "JL000"

#: the canonical axes of parallel/mesh.py — ALWAYS accepted by JL005;
#: ``*_AXIS`` constants found in the linted sources extend this whitelist
DEFAULT_AXES = frozenset({"data", "model"})


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint hit: ``path:line:col: CODE message``."""

    code: str
    message: str
    path: str
    line: int
    col: int

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.code} " \
               f"{self.message}"


#: registry: code -> rule function ``(ctx) -> Iterable[Finding]``
RULES: dict[str, Callable] = {}


def rule(code: str, name: str, summary: str):
    """Register a rule function under ``code`` (JLxxx)."""

    def deco(fn):
        fn.code, fn.name, fn.summary = code, name, summary
        if code in RULES:
            raise ValueError(f"duplicate rule code {code}")
        RULES[code] = fn
        return fn

    return deco


def dotted_name(node: ast.AST) -> str | None:
    """``jax.random.split`` for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def target_names(node: ast.AST) -> list[str]:
    """Every name an assignment target binds — flattening tuple/list
    unpacking and starred targets; attribute targets yield their dotted
    form (``self.train_step``); subscript targets yield the base name
    (mutating ``d[k]`` keeps ``d`` alive for dataflow purposes)."""
    if isinstance(node, ast.Name):
        return [node.id]
    if isinstance(node, (ast.Tuple, ast.List)):
        out: list[str] = []
        for el in node.elts:
            out.extend(target_names(el))
        return out
    if isinstance(node, ast.Starred):
        return target_names(node.value)
    if isinstance(node, ast.Attribute):
        d = dotted_name(node)
        return [d] if d is not None else []
    if isinstance(node, ast.Subscript):
        return target_names(node.value)
    return []


def walk_with_parents(tree: ast.AST) -> dict[ast.AST, ast.AST]:
    parents: dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit", "pjit.pjit"}
_PARTIAL_NAMES = {"functools.partial", "partial"}


def _is_jit_callable(node: ast.AST) -> bool:
    """Does this expression evaluate to a jit transform?  Covers ``jax.jit``
    and ``functools.partial(jax.jit, ...)``."""
    if dotted_name(node) in _JIT_NAMES:
        return True
    return (isinstance(node, ast.Call)
            and dotted_name(node.func) in _PARTIAL_NAMES
            and bool(node.args)
            and dotted_name(node.args[0]) in _JIT_NAMES)


def _enclosing_funcs(node: ast.AST, parents: dict[ast.AST, ast.AST]
                     ) -> list[ast.AST]:
    """Function defs lexically enclosing ``node``, innermost first."""
    chain = []
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            chain.append(cur)
        cur = parents.get(cur)
    return chain


def _resolve_def(name: str, call: ast.Call,
                 defs_by_name: dict[str, list],
                 parents: dict[ast.AST, ast.AST]) -> ast.FunctionDef | None:
    """The def named ``name`` that is lexically visible at ``call`` —
    with two same-named defs in different factories (this repo's
    ``step_fn`` idiom), each jit call site binds its OWN scope's def."""
    candidates = defs_by_name.get(name, [])
    if len(candidates) == 1:
        return candidates[0]
    call_chain = _enclosing_funcs(call, parents)
    best, best_depth = None, -1
    for d in candidates:
        chain = _enclosing_funcs(d, parents)
        container = chain[0] if chain else None
        if container is None:
            depth = 0  # module level: visible everywhere
        elif container in call_chain:
            depth = len(call_chain) - call_chain.index(container)
        else:
            continue  # a sibling scope's def — not visible here
        if depth >= best_depth:  # ties: later (re)definition wins
            best, best_depth = d, depth
    return best


class JitIndex:
    """Which functions in a module are jit-traced, and how.

    Three detections, mirroring how this codebase actually jits:

    * ``@jax.jit`` / ``@partial(jax.jit, ...)`` decorated defs;
    * defs passed as the first argument of a ``jax.jit(...)`` call (the
      ``return jax.jit(step_fn, ...)`` idiom of parallel/step.py),
      resolved by lexical scope and recorded together with that call's
      keywords so the donation rule can see ``donate_argnums``;
    * every def nested inside a jit body (it is part of the traced
      program).
    """

    def __init__(self, tree: ast.AST,
                 parents: dict[ast.AST, ast.AST] | None = None):
        if parents is None:
            parents = walk_with_parents(tree)
        #: root jit-traced defs (nested defs reachable by walking them)
        self.roots: list[ast.FunctionDef] = []
        #: jit-traced def -> list of (jit call node, its keywords)
        self.call_sites: dict[ast.FunctionDef,
                              list[tuple[ast.Call, list[ast.keyword]]]]
        self.call_sites = {}
        #: decorated defs -> the decorator node (for JL004 position)
        self.decorated: dict[ast.FunctionDef, ast.AST] = {}

        defs_by_name: dict[str, list] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs_by_name.setdefault(node.name, []).append(node)

        seen: set[ast.FunctionDef] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and dotted_name(node.func) in _JIT_NAMES \
                    and node.args and isinstance(node.args[0], ast.Name):
                fn = _resolve_def(node.args[0].id, node, defs_by_name,
                                  parents)
                if fn is None:
                    continue
                self.call_sites.setdefault(fn, []).append(
                    (node, node.keywords))
                if fn not in seen:
                    seen.add(fn)
                    self.roots.append(fn)
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            for deco in node.decorator_list:
                if _is_jit_callable(deco):
                    self.decorated[node] = deco
                    if node not in seen:
                        seen.add(node)
                        self.roots.append(node)
        # drop roots nested inside other roots (walking the outer one
        # already covers them; double-visits would duplicate findings)
        spans = [(r.lineno, max(r.lineno, getattr(r, "end_lineno",
                                                  r.lineno)), r)
                 for r in self.roots]
        self.roots = [
            r for (lo, hi, r) in spans
            if not any(o is not r and olo <= lo and hi <= ohi
                       for (olo, ohi, o) in spans)
        ]


@dataclasses.dataclass
class FileContext:
    """Everything a rule sees for one file."""

    path: str
    src: str
    tree: ast.AST
    parents: dict[ast.AST, ast.AST]
    jit: JitIndex
    allowed_axes: frozenset[str]

    def finding(self, code: str, node: ast.AST, message: str) -> Finding:
        return Finding(code=code, message=message, path=self.path,
                       line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0))


# --------------------------------------------------------------- suppressions

_DISABLE_RES: dict[str, re.Pattern] = {}


def _disable_re(tool: str) -> re.Pattern:
    pat = _DISABLE_RES.get(tool)
    if pat is None:
        pat = re.compile(
            rf"#\s*{tool}:\s*(disable(?:-file)?)\s*=\s*"
            r"([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)")
        _DISABLE_RES[tool] = pat
    return pat


def _iter_directives(src: str, tool: str):
    """Yield ``(lineno, col, kind, codes, text)`` for every well-formed
    ``# <tool>: disable[-file]=...`` comment, and ``(lineno, col, None,
    None, text)`` for comments that attempt the grammar but fail it.
    Both jaxlint and jaxguard share this grammar — only the tool prefix
    differs."""
    pat = _disable_re(tool)
    try:
        tokens = tokenize.generate_tokens(io.StringIO(src).readline)
        comments = [(t.start[0], t.start[1], t.string) for t in tokens
                    if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, SyntaxError, IndentationError):
        return
    for lineno, col, text in comments:
        m = pat.search(text)
        if m is None:
            # only a comment that attempts the directive grammar — the
            # tool name, a colon, and a waiver keyword — is malformed;
            # prose merely mentioning the words is not
            if re.search(rf"{tool}\s*:", text) and "disable" in text:
                yield lineno, col, None, None, text
            continue
        kind = m.group(1)
        codes = [c.strip() for c in m.group(2).split(",") if c.strip()]
        yield lineno, col, kind, codes, text


def parse_suppressions(src: str, path: str, known_codes: set[str],
                       tool: str = "jaxlint",
                       meta_code: str = META_CODE,
                       ) -> tuple[dict[int, set[str]], set[str],
                                  list[Finding]]:
    """Scan comments for the suppression grammar.

    Returns ``(line_disables, file_disables, meta_findings)`` where
    ``line_disables[lineno]`` is the set of codes waived on that line,
    ``file_disables`` the file-wide set, and ``meta_findings`` the
    ``meta_code`` reports for unknown codes named in a disable comment
    (a typo'd code silently suppressing nothing is itself a hazard).
    jaxguard reuses this with ``tool="jaxguard", meta_code="JG000"``.
    """
    line_disables: dict[int, set[str]] = {}
    file_disables: set[str] = set()
    meta: list[Finding] = []
    for lineno, col, kind, codes, text in _iter_directives(src, tool):
        if kind is None:
            meta.append(Finding(
                meta_code, f"unparseable {tool} comment: {text!r}",
                path, lineno, col))
            continue
        for code in codes:
            if code not in known_codes:
                meta.append(Finding(
                    meta_code,
                    f"unknown rule code {code!r} in {kind}= comment "
                    f"(known: {', '.join(sorted(known_codes))})",
                    path, lineno, col))
                continue
            if kind == "disable-file":
                file_disables.add(code)
            else:
                line_disables.setdefault(lineno, set()).add(code)
    return line_disables, file_disables, meta


# -------------------------------------------------------------------- driver

def collect_axis_names(trees: Iterable[ast.AST]) -> frozenset[str]:
    """Sharding axis names the linted sources define: every module-level
    ``<NAME>_AXIS = "literal"`` constant (parallel/mesh.py's DATA_AXIS /
    MODEL_AXIS, pipeline.py's PIPE_AXIS, moe.py's EXPERT_AXIS, ...)."""
    axes: set[str] = set()
    for tree in trees:
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id.endswith("_AXIS") \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                axes.add(node.value.value)
    return frozenset(axes)


def _select_rules(select: Iterable[str] | None = None,
                  ignore: Iterable[str] | None = None) -> dict:
    from . import rules as _rules  # noqa: F401  (registers on import)
    chosen = dict(RULES)
    if select:
        unknown = set(select) - set(chosen) - {META_CODE}
        if unknown:
            raise ValueError(f"unknown rule code(s): {sorted(unknown)}")
        chosen = {c: chosen[c] for c in select if c in chosen}
    for c in ignore or ():
        chosen.pop(c, None)
    return chosen


def _meta_enabled(select: Iterable[str] | None,
                  ignore: Iterable[str] | None) -> bool:
    """JL000 obeys --select/--ignore like any rule."""
    if select is not None and META_CODE not in select:
        return False
    return META_CODE not in (ignore or ())


def lint_source(src: str, path: str = "<string>",
                select: Iterable[str] | None = None,
                ignore: Iterable[str] | None = None,
                allowed_axes: Iterable[str] | None = None,
                tree: ast.AST | None = None,
                suppress: bool = True) -> list[Finding]:
    """Lint one source string; returns findings sorted by position.

    ``allowed_axes``: the sharding axis names JL005 accepts; defaults to
    the canonical ``{"data", "model"}`` plus any ``*_AXIS`` constants
    defined in ``src`` itself.
    ``tree``: pre-parsed AST of ``src``, to spare a reparse.
    ``suppress=False`` returns the raw findings with disable comments
    ignored — :func:`suppression_report` uses it to decide which
    directives still earn their keep.
    """
    chosen = _select_rules(select, ignore)
    meta_on = _meta_enabled(select, ignore)
    if tree is None:
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            if not meta_on:
                return []
            return [Finding(META_CODE, f"syntax error: {e.msg}", path,
                            e.lineno or 1, e.offset or 0)]
    if allowed_axes is None:
        axes = collect_axis_names([tree]) | DEFAULT_AXES
    else:
        axes = frozenset(allowed_axes)
    parents = walk_with_parents(tree)
    ctx = FileContext(path=path, src=src, tree=tree, parents=parents,
                      jit=JitIndex(tree, parents), allowed_axes=axes)
    findings: list[Finding] = []
    for fn in chosen.values():
        findings.extend(fn(ctx))
    line_dis, file_dis, meta = parse_suppressions(
        src, path, set(RULES) | {META_CODE})
    if not suppress:
        line_dis, file_dis = {}, set()
    findings = [
        f for f in findings
        if f.code not in file_dis
        and f.code not in line_dis.get(f.line, ())
    ]
    if meta_on:
        findings.extend(m for m in meta
                        if m.code not in file_dis
                        and m.code not in line_dis.get(m.line, ()))
    return sorted(findings, key=lambda f: (f.line, f.col, f.code))


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
        elif os.path.isdir(p):
            for dirpath, dirnames, files in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if not d.startswith(".")
                                     and d != "__pycache__")
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(dirpath, f)
        else:
            raise FileNotFoundError(p)


def lint_paths(paths: Iterable[str],
               select: Iterable[str] | None = None,
               ignore: Iterable[str] | None = None) -> list[Finding]:
    """Lint files/trees.  The JL005 axis whitelist is collected across ALL
    the linted sources first (the constants live in parallel/mesh.py but
    are consumed in other files), then each file is linted against it."""
    files = list(iter_python_files(paths))
    sources: dict[str, str] = {}
    trees: dict[str, ast.AST] = {}
    for f in files:
        with open(f, encoding="utf-8") as fh:
            sources[f] = fh.read()
        try:
            trees[f] = ast.parse(sources[f])
        except SyntaxError:
            pass  # lint_source reports it per file below
    axes = collect_axis_names(trees.values()) | DEFAULT_AXES
    findings: list[Finding] = []
    for f in files:
        findings.extend(lint_source(sources[f], path=f, select=select,
                                    ignore=ignore, allowed_axes=axes,
                                    tree=trees.get(f)))
    return sorted(findings, key=lambda x: (x.path, x.line, x.col, x.code))


def suppression_report(paths: Iterable[str]) -> list[dict]:
    """Every ``# jaxlint:``/``# jaxguard:`` disable directive under
    ``paths``, with whether it still earns its keep.

    A directive is **live** when the raw run (suppressions ignored) of
    its tool still produces at least one finding it waives — same line
    for ``disable=``, anywhere in the file for ``disable-file=``.  A
    dead directive is worse than noise: it documents a hazard that no
    longer exists and will silently swallow the *next* genuine finding
    that lands on that line.  ``jaxlint --stats`` fails the gate on
    them, printing the exact file:line to delete.
    """
    from .guard import guard_source  # lazy: guard imports this module
    from .race import race_source  # lazy: race imports this module

    files = list(iter_python_files(paths))
    sources: dict[str, str] = {}
    trees: dict[str, ast.AST] = {}
    for f in files:
        with open(f, encoding="utf-8") as fh:
            sources[f] = fh.read()
        try:
            trees[f] = ast.parse(sources[f])
        except SyntaxError:
            pass
    axes = collect_axis_names(trees.values()) | DEFAULT_AXES
    entries: list[dict] = []
    for f in files:
        src = sources[f]
        raw_by_tool = {
            "jaxlint": lint_source(src, path=f, allowed_axes=axes,
                                   tree=trees.get(f), suppress=False),
            "jaxguard": guard_source(src, path=f, tree=trees.get(f),
                                     suppress=False),
            "jaxrace": race_source(src, path=f, tree=trees.get(f),
                                   suppress=False),
        }
        for tool, raws in raw_by_tool.items():
            for lineno, _col, kind, codes, _text in \
                    _iter_directives(src, tool):
                if kind is None:
                    continue  # malformed — the meta rule already fires
                for code in codes:
                    if kind == "disable-file":
                        hits = sum(1 for r in raws if r.code == code)
                    else:
                        hits = sum(1 for r in raws
                                   if r.code == code and r.line == lineno)
                    entries.append({
                        "path": f, "line": lineno, "tool": tool,
                        "code": code, "kind": kind, "hits": hits,
                        "live": hits > 0,
                    })
    return entries


def main(argv: list[str] | None = None) -> int:
    """CLI: ``jaxlint [paths...]`` — exit 0 when clean, 1 with findings."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="jaxlint",
        description="AST-based TPU-hazard linter for jax code "
                    "(see docs/DESIGN.md 'Static analysis').")
    # default: the installed package itself, wherever jaxlint is run from
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    parser.add_argument("paths", nargs="*", default=[pkg_dir],
                        help="files or directories (default: the package)")
    parser.add_argument("--select", help="comma-separated codes to run")
    parser.add_argument("--ignore", help="comma-separated codes to skip")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule table and exit")
    parser.add_argument("--stats", action="store_true",
                        help="list every suppression directive and "
                             "contract-level allowlist entry; dead "
                             "directives (rule no longer fires) exit 1")
    args = parser.parse_args(argv)

    from . import rules as _rules  # noqa: F401  (registers on import)
    if args.list_rules:
        for code in sorted(RULES):
            fn = RULES[code]
            print(f"{code}  {fn.name}: {fn.summary}")
        return 0
    if args.stats:
        import glob as _glob
        import json as _json
        entries = suppression_report(args.paths)
        for e in entries:
            status = "live" if e["live"] else "DEAD"
            print(f"{e['path']}:{e['line']}: {e['tool']} "
                  f"{e['kind']}={e['code']} [{status}, "
                  f"{e['hits']} hit(s)]")
        from .contracts import default_contracts_dir
        for p in sorted(_glob.glob(os.path.join(default_contracts_dir(),
                                                "*.json"))):
            with open(p, encoding="utf-8") as fh:
                doc = _json.load(fh)
            for pair in doc.get("divergent_pairs") or ():
                print(f"{p}: allowlist divergent_pair "
                      f"{pair[0]}|{pair[1]} "
                      "[staleness policed by --guard check]")
        dead = [e for e in entries if not e["live"]]
        if dead:
            print(f"jaxlint --stats: {len(dead)} dead suppression(s) — "
                  "delete the directive(s) above marked DEAD",
                  file=sys.stderr)
            return 1
        return 0
    split = lambda s: [c.strip() for c in s.split(",") if c.strip()]  # noqa: E731
    try:
        findings = lint_paths(
            args.paths,
            select=split(args.select) if args.select else None,
            ignore=split(args.ignore) if args.ignore else None)
    except (FileNotFoundError, ValueError) as e:
        print(f"jaxlint: error: {e}", file=sys.stderr)
        return 2
    for f in findings:
        print(f.format())
    if findings:
        print(f"jaxlint: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    return 0
