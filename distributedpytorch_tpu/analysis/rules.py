"""jaxlint rules: the TPU failure modes this codebase has paid for.

Each rule is a function ``(ctx: FileContext) -> Iterable[Finding]``
registered under a stable ``JLxxx`` code.  Rules are deliberately
heuristic — they run on the AST with no type information — so each one is
scoped to keep false positives near zero on idiomatic jax code: hazards
that only matter inside a compiled program (host syncs, tracer branching,
float64, print) are checked only inside *jit bodies* as detected by
:class:`core.JitIndex`, while hazards that are wrong anywhere (key reuse,
unknown sharding axes, jax.debug leftovers) are checked module-wide.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Iterator

from .core import Finding, FileContext, dotted_name, rule

# --------------------------------------------------------------- shared bits

#: attribute reads on a tracer that are STATIC under jit — branching or
#: host math on these never retraces
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}


def _is_shape_derived(node: ast.AST) -> bool:
    """Expression provably derived from static tracer metadata (or
    constants) — ``x.shape[0]``, ``len(w)``, ``a.ndim - 1``..."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
        return True
    if isinstance(node, ast.Subscript):
        return _is_shape_derived(node.value)
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id == "len":
        return True
    if isinstance(node, ast.BinOp):
        return _is_shape_derived(node.left) and _is_shape_derived(node.right)
    if isinstance(node, ast.UnaryOp):
        return _is_shape_derived(node.operand)
    return False


def _names_in(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _param_names(fn: ast.FunctionDef) -> set[str]:
    a = fn.args
    names = [p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return {n for n in names if n not in ("self", "cls")}


def _assigned_names(target: ast.AST) -> Iterator[str]:
    for n in ast.walk(target):
        if isinstance(n, ast.Name):
            yield n.id


# ---------------------------------------------------------------- JL001

_HOST_SYNC_CALLS = {
    "jax.device_get": "jax.device_get materializes device values on host",
    "np.asarray": "np.asarray on a tracer forces a device->host transfer",
    "np.array": "np.array on a tracer forces a device->host transfer",
    "numpy.asarray": "numpy.asarray forces a device->host transfer",
    "numpy.array": "numpy.array forces a device->host transfer",
    "jax.block_until_ready": "blocking sync inside a traced function",
}
_SCALAR_BUILTINS = {"float", "int", "bool"}


def _tainted_names(root: ast.FunctionDef) -> set[str]:
    """Root params plus every name assigned from a param-derived
    expression, propagated to a fixed point (statement order doesn't
    matter; taint only grows)."""
    tainted = set(_param_names(root))
    changed = True
    while changed:
        changed = False
        for node in ast.walk(root):
            if not isinstance(node, (ast.Assign, ast.AnnAssign,
                                     ast.AugAssign)):
                continue
            value = node.value
            if value is None or not _taints(value, tainted):
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                for name in _assigned_names(t):
                    if name not in tainted:
                        tainted.add(name)
                        changed = True
    return tainted


@rule("JL001", "host-sync-in-jit",
      "host-device synchronization reachable from a jitted function")
def host_sync_in_jit(ctx: FileContext) -> Iterable[Finding]:
    for root in ctx.jit.roots:
        tainted = _tainted_names(root)
        for node in ast.walk(root):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item" and not node.args:
                yield ctx.finding(
                    "JL001", node,
                    ".item() inside a jitted function is a per-element "
                    "device->host round trip; keep the value on device or "
                    "read it back in bulk outside jit")
            elif name in _HOST_SYNC_CALLS \
                    and any(_taints(a, tainted) for a in node.args):
                # taint-gated: np.array([0.485, ...]) on literals is a
                # legitimate trace-time constant, not a device readback
                yield ctx.finding(
                    "JL001", node,
                    f"{name}() inside a jitted function: "
                    f"{_HOST_SYNC_CALLS[name]} — use jnp on the tracer "
                    "instead")
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "block_until_ready":
                yield ctx.finding(
                    "JL001", node,
                    ".block_until_ready() inside a jitted function is a "
                    "blocking host sync — move it outside the traced code")
            elif isinstance(node.func, ast.Name) \
                    and node.func.id in _SCALAR_BUILTINS \
                    and len(node.args) == 1 \
                    and _taints(node.args[0], tainted):
                yield ctx.finding(
                    "JL001", node,
                    f"{node.func.id}() on a traced value concretizes it "
                    "(host sync or ConcretizationTypeError); compute with "
                    "jnp scalars instead")


# ---------------------------------------------------------------- JL002

def _is_none_check(test: ast.AST) -> bool:
    """``x is None`` / ``x is not None`` — pytree STRUCTURE, static under
    jit (an optional leaf's presence never retraces)."""
    return (isinstance(test, ast.Compare)
            and all(isinstance(op, (ast.Is, ast.IsNot))
                    for op in test.ops)
            and any(isinstance(c, ast.Constant) and c.value is None
                    for c in (test.left, *test.comparators)))


def _is_structure_check(test: ast.AST, tainted: set[str]) -> bool:
    """True when every tainted name in ``test`` is consumed through a
    PYTREE-STRUCTURE predicate — ``isinstance(x, ...)`` or a string-key
    membership ``"k" in x`` — which are static at trace time (the guard
    raises/branches while tracing, never per-value)."""
    static_ids: set[int] = set()
    for sub in ast.walk(test):
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name) \
                and sub.func.id == "isinstance":
            static_ids.update(id(n) for n in ast.walk(sub))
        if isinstance(sub, ast.Compare) \
                and all(isinstance(op, (ast.In, ast.NotIn))
                        for op in sub.ops) \
                and isinstance(sub.left, ast.Constant) \
                and isinstance(sub.left.value, str):
            for c in sub.comparators:
                static_ids.update(id(n) for n in ast.walk(c))
    return all(id(n) in static_ids for n in ast.walk(test)
               if isinstance(n, ast.Name) and n.id in tainted)


@rule("JL002", "tracer-control-flow",
      "Python if/while on tracer-derived values retraces per value")
def tracer_control_flow(ctx: FileContext) -> Iterable[Finding]:
    for root in ctx.jit.roots:
        tainted = set(_param_names(root))
        yield from _walk_taint(ctx, root.body, tainted)


def _taints(node: ast.AST, tainted: set[str]) -> bool:
    """Does evaluating ``node`` produce a tracer-derived value?  Static
    metadata (.shape/.ndim/len) and None-checks break the chain."""
    if _is_shape_derived(node):
        return False
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in tainted:
            # a tainted name under a static-attr read doesn't taint; walk
            # can't see context, so re-test the smallest enclosing pieces
            return not _only_static_uses(node, tainted)
    return False


def _only_static_uses(node: ast.AST, tainted: set[str]) -> bool:
    """True when every tainted Name inside ``node`` is consumed through a
    static attribute (``x.shape``...) or ``len(x)``."""
    static_spans: list[tuple[int, int]] = []
    for sub in ast.walk(node):
        if (isinstance(sub, ast.Attribute)
                and sub.attr in _STATIC_ATTRS) or (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Name)
                and sub.func.id == "len"):
            for n in ast.walk(sub):
                if isinstance(n, ast.Name) and n.id in tainted:
                    static_spans.append((n.lineno, n.col_offset))
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id in tainted:
            if (n.lineno, n.col_offset) not in static_spans:
                return False
    return True


def _walk_taint(ctx: FileContext, body: list[ast.stmt],
                tainted: set[str]) -> Iterator[Finding]:
    """Forward taint pass: params are tracers; assignments propagate;
    if/while tests on tainted values are flagged.  Taint only grows
    (branches are not merged) — conservative and order-robust."""
    for stmt in body:
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = stmt.value
            if value is not None and _taints(value, tainted):
                targets = stmt.targets if isinstance(stmt, ast.Assign) \
                    else [stmt.target]
                for t in targets:
                    tainted.update(_assigned_names(t))
        elif isinstance(stmt, (ast.If, ast.While)):
            if not _is_none_check(stmt.test) \
                    and not _is_structure_check(stmt.test, tainted) \
                    and _taints(stmt.test, tainted):
                kind = "if" if isinstance(stmt, ast.If) else "while"
                yield ctx.finding(
                    "JL002", stmt,
                    f"Python `{kind}` on a tracer-derived value: the "
                    "branch is decided at TRACE time, recompiling per "
                    "concrete value (or raising under jit) — use "
                    "jnp.where / lax.cond / lax.while_loop")
            yield from _walk_taint(ctx, stmt.body, tainted)
            yield from _walk_taint(ctx, stmt.orelse, tainted)
            continue
        elif isinstance(stmt, ast.For):
            if _taints(stmt.iter, tainted):
                yield ctx.finding(
                    "JL002", stmt,
                    "Python `for` over a tracer-derived iterable unrolls "
                    "at trace time and retraces per length — use "
                    "lax.scan / lax.fori_loop")
            yield from _walk_taint(ctx, stmt.body, tainted)
            yield from _walk_taint(ctx, stmt.orelse, tainted)
            continue
        # recurse into other compound statements, nested defs included
        # (a def nested in a jit body is traced with the same closures;
        # its params shadow, so drop them from the view it sees)
        for child_body, shadow in _child_bodies(stmt):
            yield from _walk_taint(ctx, child_body, tainted - shadow)


def _child_bodies(stmt: ast.stmt
                  ) -> Iterator[tuple[list[ast.stmt], set[str]]]:
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
        yield stmt.body, _param_names(stmt)
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        yield stmt.body, set()
    elif isinstance(stmt, ast.Try):
        for b in (stmt.body, stmt.orelse, stmt.finalbody,
                  *[h.body for h in stmt.handlers]):
            yield b, set()


# ---------------------------------------------------------------- JL003

#: jax.random functions that MANAGE keys rather than consume them
_KEY_MANAGERS = {"split", "fold_in", "PRNGKey", "key", "key_data",
                 "wrap_key_data", "clone"}

#: parameter names treated as live keys without a visible binding
_KEY_PARAM_RE = re.compile(r"(^|_)(rng|key|prng_key|prngkey)$")


def _random_module_aliases(tree: ast.AST) -> frozenset[str]:
    """Local names the random module is reachable under: ``random`` always
    (``jax.random.split`` / ``from jax import random``), plus any alias from
    ``import jax.random as jr`` or ``from jax import random as jrandom``."""
    aliases = {"random"}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            aliases.update(a.asname for a in node.names
                           if a.name == "jax.random" and a.asname)
        elif isinstance(node, ast.ImportFrom) and node.module == "jax":
            aliases.update(a.asname for a in node.names
                           if a.name == "random" and a.asname)
    return frozenset(aliases)


def _random_fn(call: ast.Call,
               aliases: frozenset[str] = frozenset({"random"})
               ) -> str | None:
    """'split' for ``jax.random.split(...)``-shaped calls, else None."""
    name = dotted_name(call.func)
    if name is None:
        return None
    parts = name.split(".")
    if len(parts) >= 2 and parts[-2] in aliases:
        return parts[-1]
    return None


@rule("JL003", "prng-discipline",
      "PRNG key consumed twice without a split, or PRNGKey(const) in a loop")
def prng_discipline(ctx: FileContext) -> Iterable[Finding]:
    aliases = _random_module_aliases(ctx.tree)
    # per-scope reuse analysis
    scopes = [n for n in ast.walk(ctx.tree)
              if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for scope in scopes:
        yield from _check_key_reuse(ctx, scope, aliases)
    # PRNGKey(constant) under a loop, anywhere in the module (each call
    # reported once, however deeply the loops nest)
    reported: set[ast.AST] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.For, ast.While)):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) \
                        and sub not in reported \
                        and _random_fn(sub, aliases) in ("PRNGKey", "key") \
                        and sub.args \
                        and isinstance(sub.args[0], ast.Constant):
                    reported.add(sub)
                    yield ctx.finding(
                        "JL003", sub,
                        "PRNGKey(constant) inside a loop yields the SAME "
                        "stream every iteration — split one key outside "
                        "the loop (or fold_in the loop index)")


def _stmt_exprs(stmt: ast.stmt) -> Iterator[ast.expr]:
    """The statement's OWN expressions — child statement bodies excluded
    (they are walked separately, so each expression is seen exactly once)."""
    for _field, value in ast.iter_fields(stmt):
        if isinstance(value, ast.expr):
            yield value
        elif isinstance(value, list):
            for v in value:
                if isinstance(v, ast.expr):
                    yield v
                elif isinstance(v, ast.withitem):
                    yield v.context_expr
                elif isinstance(v, ast.keyword):
                    yield v.value


def _innermost_call(node: ast.AST, parents: dict, stop: ast.AST
                    ) -> ast.Call | None:
    """Nearest enclosing Call of ``node``, not ascending past ``stop`` —
    ``split(key)`` inside ``deg2rad(uniform(key))`` attributes the use to
    ``uniform``, the call that actually receives the key."""
    cur = parents.get(node)
    while cur is not None and cur is not stop:
        if isinstance(cur, ast.Call):
            return cur
        cur = parents.get(cur)
    return None


def _check_key_reuse(ctx: FileContext, scope: ast.FunctionDef,
                     aliases: frozenset[str] = frozenset({"random"})
                     ) -> Iterator[Finding]:
    """Linear walk of one function: names bound from jax.random key ops
    are 'live keys'; passing a live key to any call consumes it (split /
    fold_in are the sanctioned re-uses); a second consumption without an
    intervening rebind is the classic silent-correlation bug."""
    consumed: dict[str, ast.AST] = {}   # key name -> first consuming node
    # live keys: names bound from jax.random key ops, plus — in functions
    # that visibly use jax.random — parameters that are unmistakably keys
    # by name (the `def f(key): two draws from key` shape is THE classic
    # reuse bug).  Functions with no jax.random call in sight get no
    # name-based seeding: an `rng` there is likely a numpy Generator.
    uses_jax_random = any(
        isinstance(n, ast.Call) and _random_fn(n, aliases) is not None
        for n in ast.walk(scope))
    keys: set[str] = {p for p in _param_names(scope)
                      if _KEY_PARAM_RE.search(p)} if uses_jax_random \
        else set()

    def handle_stmt(stmt: ast.stmt) -> Iterator[Finding]:
        for expr in _stmt_exprs(stmt):
            for name_node in ast.walk(expr):
                if not (isinstance(name_node, ast.Name)
                        and isinstance(name_node.ctx, ast.Load)
                        and name_node.id in keys):
                    continue
                call = _innermost_call(name_node, ctx.parents, stmt)
                if call is None:
                    continue  # bare aliasing, not a draw
                if name_node is call.func or (
                        isinstance(call.func, ast.Attribute)
                        and name_node in ast.walk(call.func)):
                    continue  # key.something(...) — not an argument use
                fn = _random_fn(call, aliases)
                if fn in _KEY_MANAGERS:
                    continue  # split/fold_in are the sanctioned uses
                prior = consumed.get(name_node.id)
                if prior is not None:
                    yield ctx.finding(
                        "JL003", name_node,
                        f"key {name_node.id!r} already consumed at line "
                        f"{prior.lineno} and used again without an "
                        "intervening jax.random.split — reusing a key "
                        "silently correlates the two draws")
                else:
                    consumed[name_node.id] = name_node
        # (re)bindings AFTER uses within the statement: x, y = split(x)
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            value = stmt.value
            # unwrap subscripts: `key = split(key)[0]` rebinds a fresh key
            core = value
            while isinstance(core, ast.Subscript):
                core = core.value
            is_key_value = isinstance(core, ast.Call) \
                and _random_fn(core, aliases) in _KEY_MANAGERS
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            for t in targets:
                for name in _assigned_names(t):
                    if is_key_value:
                        keys.add(name)
                    else:
                        keys.discard(name)  # retired from tracking
                    consumed.pop(name, None)

    def _terminates(body: list[ast.stmt]) -> bool:
        return bool(body) and isinstance(body[-1], (ast.Return, ast.Raise,
                                                    ast.Break, ast.Continue))

    def walk_branch(body: list[ast.stmt]) -> tuple[list, dict]:
        """Walk one ALTERNATE path without mutating the shared state:
        returns (findings, state-after-on-fall-through), where a branch
        that cannot fall through contributes nothing to the
        continuation (the classic early-return shape)."""
        snapshot = dict(consumed)
        findings = list(walk_body(body))
        after = snapshot if _terminates(body) else dict(consumed)
        consumed.clear()
        consumed.update(snapshot)
        return findings, dict(after)

    def walk_body(body: list[ast.stmt]) -> Iterator[Finding]:
        for stmt in body:
            # nested defs get their own _check_key_reuse invocation
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield from handle_stmt(stmt)
            if isinstance(stmt, ast.If):
                # mutually exclusive paths: each walks from the pre-if
                # state (one branch's draw must not read as the other's
                # reuse); the continuation state is the UNION of the
                # fall-through branch states — replacing, not updating,
                # so a key rebound in both branches comes back clean
                body_findings, after_body = walk_branch(stmt.body)
                else_findings, after_else = walk_branch(stmt.orelse)
                yield from body_findings
                yield from else_findings
                consumed.clear()
                consumed.update(after_body)
                consumed.update(after_else)
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                # two linear passes ~= two unrolled iterations: a key
                # consumed each iteration without an intervening rebind
                # surfaces as a reuse on the second pass (duplicates are
                # collapsed by the scope-level position filter)
                yield from walk_body(stmt.body)
                yield from walk_body(stmt.body)
                yield from walk_body(stmt.orelse)
            else:
                # with/try bodies are the SAME path, not alternatives:
                # walk them inline so their rebinds clear state for the
                # continuation; only except handlers are alternates
                for field in ("body", "orelse", "finalbody"):
                    child = getattr(stmt, field, None)
                    if child:
                        yield from walk_body(child)
                for h in getattr(stmt, "handlers", ()) or ():
                    findings, after = walk_branch(h.body)
                    yield from findings
                    consumed.update(after)

    emitted: set[tuple[int, int]] = set()
    for f in walk_body(list(scope.body)):
        if (f.line, f.col) not in emitted:
            emitted.add((f.line, f.col))
            yield f


# ---------------------------------------------------------------- JL004

def _updates_own_arg(fn: ast.FunctionDef) -> str | None:
    """Name of a parameter the function returns an updated version of —
    the ``state.replace(...)`` / ``optax.apply_updates(state, ...)``
    step-function shape whose old buffers are dead after the call."""
    params = _param_names(fn)
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "replace" \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id in params:
            return node.func.value.id
        name = dotted_name(node.func)
        if name is not None and name.split(".")[-1] == "apply_updates" \
                and node.args and isinstance(node.args[0], ast.Name) \
                and node.args[0].id in params:
            return node.args[0].id
    return None


@rule("JL004", "donation-drift",
      "jit of a state-updating step without donate_argnums")
def donation_drift(ctx: FileContext) -> Iterable[Finding]:
    for fn, sites in ctx.jit.call_sites.items():
        arg = _updates_own_arg(fn)
        if arg is None:
            continue
        for call, keywords in sites:
            if not any(kw.arg in ("donate_argnums", "donate_argnames")
                       for kw in keywords):
                yield ctx.finding(
                    "JL004", call,
                    f"jit of {fn.name!r} returns an updated {arg!r} but "
                    "donates nothing: the old buffers stay live across "
                    "the call, doubling peak HBM — pass donate_argnums")
    for fn_node, deco in ctx.jit.decorated.items():
        arg = _updates_own_arg(fn_node)
        if arg is None:
            continue
        kws = deco.keywords if isinstance(deco, ast.Call) else []
        if not any(kw.arg in ("donate_argnums", "donate_argnames")
                   for kw in kws):
            yield ctx.finding(
                "JL004", deco,
                f"jitted {fn_node.name!r} returns an updated {arg!r} but "
                "donates nothing: the old buffers stay live across the "
                "call, doubling peak HBM — use "
                "partial(jax.jit, donate_argnums=...)")


# ---------------------------------------------------------------- JL005

_PSPEC_NAMES = {"P", "PartitionSpec", "jax.sharding.PartitionSpec",
                "sharding.PartitionSpec"}


@rule("JL005", "sharding-axis-drift",
      "PartitionSpec axis name not defined by the mesh modules")
def sharding_axis_drift(ctx: FileContext) -> Iterable[Finding]:
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and dotted_name(node.func) in _PSPEC_NAMES):
            continue
        for arg in node.args:
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Constant) \
                        and isinstance(sub.value, str) \
                        and sub.value not in ctx.allowed_axes:
                    yield ctx.finding(
                        "JL005", sub,
                        f"PartitionSpec axis {sub.value!r} is not a mesh "
                        "axis defined by the *_AXIS constants "
                        f"({', '.join(sorted(ctx.allowed_axes))}) — a "
                        "typo'd axis silently replicates instead of "
                        "sharding")


# ---------------------------------------------------------------- JL006

@rule("JL006", "float64-leak",
      "float64 flowing into device code (TPUs have no f64 units)")
def float64_leak(ctx: FileContext) -> Iterable[Finding]:
    # jnp.float64 anywhere: without x64 it silently truncates to f32;
    # with x64 it software-emulates at ~25x cost on TPU
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Attribute) and node.attr == "float64" \
                and dotted_name(node) in ("jnp.float64",
                                          "jax.numpy.float64"):
            yield ctx.finding(
                "JL006", node,
                "jnp.float64 is a silent f32 truncation without "
                "jax_enable_x64 and a ~25x software-emulated cost with it "
                "— use jnp.float32 (or explicit f32 accumulation)")
        if isinstance(node, ast.Call) \
                and dotted_name(node.func) == "jax.config.update" \
                and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and node.args[0].value == "jax_enable_x64":
            yield ctx.finding(
                "JL006", node,
                "jax_enable_x64 flips EVERY default dtype to 64-bit — "
                "device code pays software-emulated f64 on TPU; scope "
                "precision per-array instead")
    # inside jit bodies: numpy float64 constructions become device
    # constants that either upcast the program or truncate silently
    for root in ctx.jit.roots:
        for node in ast.walk(root):
            name = dotted_name(node) if isinstance(node, ast.Attribute) \
                else None
            if name in ("np.float64", "numpy.float64"):
                yield ctx.finding(
                    "JL006", node,
                    "np.float64 inside a jitted function: the f64 "
                    "constant upcasts downstream math (then truncates on "
                    "TPU) — use np.float32/jnp.float32")
            if isinstance(node, ast.Constant) and node.value == "float64":
                yield ctx.finding(
                    "JL006", node,
                    "'float64' dtype inside a jitted function — TPUs "
                    "have no f64; use 'float32'")


# ---------------------------------------------------------------- JL007

_DEBUG_CALLS = {
    "jax.debug.print": "jax.debug.print forces a host callback every "
                       "step — remove it or gate it behind a debug flag",
    "jax.debug.breakpoint": "jax.debug.breakpoint halts every device "
                            "program — remove before committing",
    "pdb.set_trace": "pdb left in committed code",
}


@rule("JL007", "debug-leftover",
      "leftover debug statements (jax.debug.print, breakpoint, print-in-jit)")
def debug_leftover(ctx: FileContext) -> Iterable[Finding]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if name in _DEBUG_CALLS:
            yield ctx.finding("JL007", node, _DEBUG_CALLS[name])
        elif isinstance(node.func, ast.Name) \
                and node.func.id == "breakpoint":
            yield ctx.finding("JL007", node, "breakpoint() left in "
                              "committed code")
    for root in ctx.jit.roots:
        for node in ast.walk(root):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id == "print":
                yield ctx.finding(
                    "JL007", node,
                    "print() inside a jitted function runs at TRACE time "
                    "only (once, with tracers) — it never sees runtime "
                    "values; delete it or use logging outside jit")


# ---------------------------------------------------------------- JL008

_IMPLICIT_ARRAY_CTORS = {"jnp.array", "jnp.asarray",
                         "jax.numpy.array", "jax.numpy.asarray"}


def _is_literalish(node: ast.AST) -> bool:
    """A value that BUILDS a new constant — list/tuple displays, numeric
    literals, and arithmetic over them.  ``jnp.asarray(x)`` of an
    existing array preserves x's dtype (no new f32 constant), so names
    and calls are out of scope for JL008."""
    if isinstance(node, ast.Constant):
        return not isinstance(node.value, str)
    if isinstance(node, (ast.List, ast.Tuple)):
        return all(_is_literalish(e) for e in node.elts)
    if isinstance(node, ast.UnaryOp):
        return _is_literalish(node.operand)
    if isinstance(node, ast.BinOp):
        return _is_literalish(node.left) and _is_literalish(node.right)
    return False


@rule("JL008", "implicit-dtype-array",
      "jnp.array/asarray of literals without an explicit dtype inside "
      "jit (silent f32 upcast)")
def implicit_dtype_array(ctx: FileContext) -> Iterable[Finding]:
    """The AST-level mirror of jaxaudit's IR dtype-flow check (JA002):
    inside a traced program, ``jnp.array([...])`` defaults the NEW
    constant to float32, and the first op mixing it with a bf16 tensor
    silently promotes that op — and everything downstream — to f32.
    An explicit ``dtype=`` (second positional argument counts: that IS
    the dtype parameter) states the precision on the constant itself,
    where the bf16 path can see it.  Scoped to literal-built values:
    ``jnp.asarray(x)`` of an existing array preserves its dtype and is
    not flagged."""
    for root in ctx.jit.roots:
        for node in ast.walk(root):
            if not (isinstance(node, ast.Call)
                    and dotted_name(node.func) in _IMPLICIT_ARRAY_CTORS):
                continue
            if not (node.args and _is_literalish(node.args[0])):
                continue
            has_dtype = len(node.args) >= 2 or any(
                kw.arg == "dtype" for kw in node.keywords)
            if not has_dtype:
                name = dotted_name(node.func)
                yield ctx.finding(
                    "JL008", node,
                    f"{name}() of literals without dtype= inside a "
                    "jitted function creates a float32 (or weakly-typed) "
                    "constant that silently upcasts bf16 math downstream "
                    "— pass dtype= explicitly (e.g. x.dtype)")
