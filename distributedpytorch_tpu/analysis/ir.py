"""jaxaudit: IR-level auditing of the hot compiled programs.

jaxlint (:mod:`rules`) reads Python source; the hazards that actually
cost step time only exist in the traced jaxpr and the compiled HLO —
a silent f32 upcast in the bf16 path, a dead output the trainer keeps
alive, donation that fails to alias, collective bloat on the mesh axes.
This module traces the REAL jitted callables (the trainer's train/eval
steps, the serve buckets' forwards) through the process-wide
:mod:`telemetry.lowering` cache and walks the program itself:

* **collective inventory** — psum/all_gather/psum_scatter/ppermute/
  all_to_all equation counts per mesh axis from the jaxpr (explicit
  shard_map collectives), plus all-reduce/all-gather/reduce-scatter/
  collective-permute/all-to-all op counts from the compiled HLO (the
  collectives GSPMD inserts — the structure arxiv's distributed-CNN
  scaling work shows dominates efficiency);
* **dtype flow** (JA002) — f32 equations fed by a bf16→f32 upcast whose
  consumer is not in the allowlisted accumulation set, and (the
  quantized-serving twin) int8→f32 dequantization converts whose
  consumer is undeclared — a quantized kernel's float form must only
  ever feed its declared dequant point (serve/quantize.QuantPolicy);
* **dead / duplicate outputs** (JA003/JA004) — outputs with no input
  dependence (baked constants the caller re-fetches every step) and the
  same value returned twice;
* **large baked constants** (JA005) — closure arrays captured into the
  trace (a captured dataset or index table rides every dispatch);
* **donation effectiveness** (JA006) — declared donations
  (``args_info``) vs the bytes the compiled program actually aliased
  (``memory_analysis().alias_size_in_bytes``): ``donate_argnums`` that
  fails to alias silently doubles peak HBM.

The report is JSON-able; :mod:`contracts` pins it platform-keyed under
``tests/contracts/`` and fails CI on drift.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Iterator

#: jaxpr-level collective primitives (psum2 is shard_map's psum)
_COLLECTIVE_PRIMS = {
    "psum": "psum",
    "psum2": "psum",
    "all_gather": "all_gather",
    "all_to_all": "all_to_all",
    "ppermute": "ppermute",
    "psum_scatter": "psum_scatter",
    "pmax": "pmax",
    "pmin": "pmin",
}

#: HLO ops counted in the compiled module (sync + async -start forms)
_HLO_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                    "collective-permute", "all-to-all")

#: f32 primitives allowed to consume a bf16→f32 upcast: reductions and
#: matmul/conv accumulation (widening the accumulator is the POINT of
#: mixed precision), re-converts and gradient plumbing, plus pure
#: layout/movement ops (reshape/transpose/slice/...) that do no f32
#: arithmetic — they carry the value, they don't compute on it.
#: Everything else computing in f32 on upcast bf16 data is paying 2x
#: bytes for math the bf16 units could do.
DEFAULT_F32_ACCUM_ALLOW = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    # cross-device reductions are reductions: a bf16 gradient
    # contribution upcast into an f32 psum (the bucketed all-reduce)
    # is the mixed-precision master-grad accumulation, device-spanning
    "psum", "psum2", "pmax", "pmin",
    "dot_general", "conv_general_dilated",
    "convert_element_type", "reduce_precision", "stop_gradient",
    # layout/movement, no arithmetic
    "reshape", "transpose", "broadcast_in_dim", "squeeze",
    "expand_dims", "slice", "dynamic_slice", "dynamic_update_slice",
    "concatenate", "rev", "gather", "pad", "copy",
})

#: constants above this many bytes raise JA005 (1 MiB — an f32 image or
#: a class-weight table is fine; a captured dataset is not)
DEFAULT_LARGE_CONST_BYTES = 1 << 20


@dataclasses.dataclass(frozen=True)
class AuditFinding:
    """One IR-level hazard: ``CODE[class] message``."""

    code: str       # JAxxx
    cls: str        # stable class key (contract-pinned count)
    message: str

    def format(self) -> str:
        return f"{self.code}[{self.cls}] {self.message}"


#: the closed set of finding classes a contract pins counts for
FINDING_CLASSES = ("dtype_upcast", "dead_output", "duplicate_output",
                   "large_const", "donation")


# ------------------------------------------------------------- jaxpr walking

def _jaxprs_in(value) -> Iterator:
    """Jaxprs nested inside one eqn param value (Jaxpr, ClosedJaxpr, or
    lists/tuples of either)."""
    if hasattr(value, "eqns"):
        yield value
    elif hasattr(value, "jaxpr"):
        yield from _jaxprs_in(value.jaxpr)
    elif isinstance(value, (list, tuple)):
        for v in value:
            yield from _jaxprs_in(v)


def iter_jaxprs(jaxpr) -> Iterator:
    """``jaxpr`` and every jaxpr nested in its equations' params
    (scan/cond/pjit/shard_map bodies, custom_vjp branches, ...)."""
    yield jaxpr
    for eqn in jaxpr.eqns:
        for v in eqn.params.values():
            for sub in _jaxprs_in(v):
                yield from iter_jaxprs(sub)


def iter_eqns(jaxpr) -> Iterator:
    for j in iter_jaxprs(jaxpr):
        yield from j.eqns


def _is_literal(atom) -> bool:
    return hasattr(atom, "val")  # core.Literal; Vars carry no .val


# --------------------------------------------------------------- inventories

def collective_inventory(closed_jaxpr) -> dict:
    """``{primitive: {axis: count}}`` over every (nested) equation.
    shard_map's ``psum2`` reports as ``psum``; an axis jax left implicit
    reports as ``"?"``."""
    inv: dict[str, dict[str, int]] = {}
    for eqn in iter_eqns(closed_jaxpr.jaxpr):
        name = _COLLECTIVE_PRIMS.get(eqn.primitive.name)
        if name is None:
            continue
        axes = eqn.params.get("axes")
        if axes is None:
            axes = eqn.params.get("axis_name")
        if axes is None:
            axes = ("?",)
        if not isinstance(axes, (tuple, list)):
            axes = (axes,)
        per_axis = inv.setdefault(name, {})
        for ax in axes:
            per_axis[str(ax)] = per_axis.get(str(ax), 0) + 1
    return inv


def hlo_collective_counts(compiled) -> dict | None:
    """Collective op counts in the compiled module's HLO text — the
    all-reduces GSPMD inserted for sharded programs, invisible at the
    jaxpr level.  None when the text is unavailable.

    Each base op's count covers BOTH forms (sync + async ``-start``) —
    the total collective volume, stable across a backend flipping its
    async lowering.  When async forms are present, a separate
    ``<op>-start`` key additionally reports just those: the overlap
    signal (an async-started collective is one the scheduler can hide
    behind compute; its count dropping to zero means the collectives
    re-serialized).  Backends that lower everything synchronously (the
    cpu8 tier-1 topology) emit no ``-start`` keys, so pre-existing
    contracts are unaffected."""
    try:
        text = compiled.as_text()
    except Exception:
        return None
    if not text:
        return None
    counts = {}
    for op in _HLO_COLLECTIVES:
        n = len(re.findall(rf" {op}(?:-start)?\(", text))
        if n:
            counts[op] = n
        n_start = len(re.findall(rf" {op}-start\(", text))
        if n_start:
            counts[f"{op}-start"] = n_start
    return counts


def async_start_count(hlo_counts: dict | None) -> int:
    """Total async ``-start`` collectives in one HLO inventory — the
    scalar the overlap contracts gate on."""
    if not hlo_counts:
        return 0
    return sum(n for op, n in hlo_counts.items() if op.endswith("-start"))


# ------------------------------------------- per-mesh-axis HLO attribution

def _parse_group_list(body: str) -> list[list[int]]:
    """``{0,1},{2,3}`` (the inside of an explicit replica_groups or
    source_target_pairs attribute) -> ``[[0,1],[2,3]]``."""
    return [[int(x) for x in g.split(",") if x.strip() != ""]
            for g in re.findall(r"\{([\d,\s]*)\}", body)]


def _parse_iota_groups(shape: str, dims: str, perm: str | None
                       ) -> list[list[int]]:
    """XLA's iota replica-group format ``[G,S]<=[dims]`` (optionally
    ``T(perm)``): the flat device list is
    ``transpose(reshape(arange(prod(dims)), dims), perm)`` reshaped to
    ``(G, S)`` — each row one group."""
    import numpy as np

    g, s = (int(x) for x in shape.split(","))
    d = [int(x) for x in dims.split(",")]
    ids = np.arange(int(np.prod(d)), dtype=np.int64).reshape(d)
    if perm:
        ids = ids.transpose([int(x) for x in perm.split(",")])
    return ids.reshape(g, s).tolist()


_GROUPS_RE = re.compile(
    r"replica_groups=(?:\{((?:\{[\d,\s]*\},?)*)\}"
    r"|\[([\d,]+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?)")
_PAIRS_RE = re.compile(r"source_target_pairs=\{((?:\{[\d,\s]*\},?)*)\}")


def _axis_groups(axes: "dict[str, int]") -> dict:
    """Expected replica-group sets per mesh axis, for the row-major
    device order every mesh in this framework uses (``make_mesh``
    reshapes ``jax.devices()``): axis k's groups hold the device ids
    reached by varying ONLY coordinate k."""
    import itertools

    import numpy as np

    sizes = list(axes.values())
    ids = np.arange(int(np.prod(sizes)), dtype=np.int64).reshape(sizes)
    out = {}
    for k, name in enumerate(axes):
        groups = set()
        other = [range(s) for i, s in enumerate(sizes) if i != k]
        for coord in itertools.product(*other):
            idx = list(coord)
            idx.insert(k, slice(None))
            groups.add(frozenset(int(x) for x in ids[tuple(idx)].ravel()))
        out[name] = groups
    return out


def _classify_groups(groups: list[list[int]], expected: dict,
                     n_devices: int) -> str:
    """One collective's replica groups -> the mesh axis they span:
    ``"data"``/``"model"`` for exact single-axis group sets, ``"global"``
    for one all-device group, ``"other"`` for anything else (sub-axis or
    mixed groupings)."""
    gset = frozenset(frozenset(g) for g in groups if g)
    if not gset:
        return "other"
    for axis, want in expected.items():
        if gset == want:
            return axis
    if gset == {frozenset(range(n_devices))}:
        return "global"
    return "other"


def _classify_pairs(pairs: list[list[int]], axes: "dict[str, int]"
                    ) -> str:
    """A collective-permute's source→target pairs -> the one mesh axis
    every hop moves along (``"other"`` when hops mix axes)."""
    import numpy as np

    sizes = list(axes.values())
    n = int(np.prod(sizes))
    moved = set()
    for src, dst in pairs:
        if not (0 <= src < n and 0 <= dst < n):
            return "other"
        cs = np.unravel_index(src, sizes)
        cd = np.unravel_index(dst, sizes)
        diff = [i for i, (a, b) in enumerate(zip(cs, cd)) if a != b]
        if len(diff) != 1:
            return "other"
        moved.add(diff[0])
    if len(moved) != 1:
        return "other"
    return list(axes)[moved.pop()]


def _collective_line_labels(compiled, mesh_axes: "dict[str, int]"
                            ) -> "list[tuple[str, str]] | None":
    """``[(op, axis_label), ...]`` for every collective line of the
    compiled module's HLO text, **in line order** — the one walk both
    :func:`mesh_axis_collective_counts` (aggregate) and
    :func:`mesh_axis_collective_schedule` (ordered) are views of.

    Line order is the order XLA's scheduler emitted the ops, i.e. issue
    order; async ``-start`` forms fold in at their issue point (the
    matching ``-done`` lines never match the op regex).  ``None`` when
    the HLO text is unavailable.
    """
    import numpy as np

    try:
        text = compiled.as_text()
    except Exception:
        return None
    if not text:
        return None
    axes = dict(mesh_axes)
    n = int(np.prod(list(axes.values())))
    expected = _axis_groups(axes)
    labels: list[tuple[str, str]] = []
    op_re = re.compile(
        rf" ({'|'.join(_HLO_COLLECTIVES)})(?:-start)?\(")
    for line in text.splitlines():
        m = op_re.search(line)
        if not m:
            continue
        op = m.group(1)
        gm = _GROUPS_RE.search(line)
        pm = _PAIRS_RE.search(line)
        if gm is not None:
            if gm.group(1) is not None:
                groups = _parse_group_list(gm.group(1))
            else:
                groups = _parse_iota_groups(gm.group(2), gm.group(3),
                                            gm.group(4))
            if not groups:
                groups = [list(range(n))]  # replica_groups={} = all
            label = _classify_groups(groups, expected, n)
        elif pm is not None:
            label = _classify_pairs(_parse_group_list(pm.group(1)), axes)
        else:
            label = "other"
        labels.append((op, label))
    return labels


def _counts_from_labels(labels: "list[tuple[str, str]]") -> dict:
    counts: dict[str, dict[str, int]] = {}
    for op, label in labels:
        per = counts.setdefault(op, {})
        per[label] = per.get(label, 0) + 1
    return counts


def _schedule_from_labels(labels: "list[tuple[str, str]]") -> dict:
    from .spmd import rle

    seqs: dict[str, list[str]] = {}
    for op, label in labels:
        seqs.setdefault(label, []).append(op)
    return {label: rle(seq) for label, seq in sorted(seqs.items())}


def mesh_axis_collective_counts(compiled, mesh_axes: "dict[str, int]"
                                ) -> dict | None:
    """``{op: {axis: count}}`` over the compiled module's collectives,
    each attributed to the mesh axis its replica groups (or permute
    pairs) span — the pin that makes "this 2-D step really communicates
    over ``model``" a checkable contract fact instead of an aggregate op
    count a replicated regression could imitate.

    ``mesh_axes`` is the ordered ``{axis_name: size}`` of the mesh the
    program was built on (row-major device order, as ``make_mesh``
    lays it out).  Handles XLA's explicit (``{{0,1},{2,3}}``) and iota
    (``[4,2]<=[8]``, ``[2,4]<=[4,2]T(1,0)``) group encodings plus
    ``source_target_pairs``.  Sync and async ``-start`` forms count
    under the base op.  ``None`` when the HLO text is unavailable.
    """
    labels = _collective_line_labels(compiled, mesh_axes)
    return None if labels is None else _counts_from_labels(labels)


def mesh_axis_collective_schedule(compiled, mesh_axes: "dict[str, int]"
                                  ) -> dict | None:
    """``{axis: [op, "op*N", ...]}`` — the **ordered** per-mesh-axis
    collective sequence of the compiled program, run-length encoded
    (:func:`spmd.rle`) so train-step-scale pins stay reviewable.

    This is the jaxguard JG002 substrate: under the lockstep-collective
    model, two programs that hosts could run as alternates of the same
    dispatch point must issue the identical op sequence on every mesh
    axis they share — the aggregate counts can match while a reordering
    still deadlocks the pod at the first mismatched op.  Labels beyond
    the named axes (``global``, ``other``) get schedules too: a
    global-group all-reduce is a sync point every host must reach in the
    same position.  ``None`` when the HLO text is unavailable.
    """
    labels = _collective_line_labels(compiled, mesh_axes)
    return None if labels is None else _schedule_from_labels(labels)


# ------------------------------------------------------------ dtype findings

def _has_subjaxpr(eqn) -> bool:
    return any(True for v in eqn.params.values() for _ in _jaxprs_in(v))


#: convert_element_type (src, dst) pairs JA002 polices.  bf16→f32 is
#: the mixed-precision accumulation flow (train/precision.py); int8→f32
#: is the weight-dequantization flow of the quantized serve forwards
#: (serve/quantize.py) — an int8 constant's float form must only ever
#: feed the declared dequant multiply.  Deliberately NOT here: the
#: wider integer/index zoo (int32 iota/gather indices convert to float
#: in ordinary host-free arithmetic all the time and flagging them
#: would make every pre-existing contract pin noise).
_JA002_FLOWS = {
    ("bfloat16", "float32"):
        ("bf16{shape} upcast to f32 consumed by non-accumulation "
         "op(s) {bad} — f32 math on the bf16 path pays 2x bytes; keep "
         "it bf16 or allowlist a real accumulation"),
    ("int8", "float32"):
        ("int8{shape} dequantized to f32 consumed by undeclared op(s) "
         "{bad} — a quantized kernel's float form must only feed its "
         "declared dequant point (QuantPolicy.ja002_allow), or the "
         "4x-bytes win silently leaks"),
}


def dtype_upcast_findings(closed_jaxpr,
                          allow: frozenset = DEFAULT_F32_ACCUM_ALLOW
                          ) -> list[AuditFinding]:
    """Policed ``convert_element_type`` equations (:data:`_JA002_FLOWS`:
    bf16→f32 upcasts, int8→f32 dequants) whose result feeds a primitive
    outside the accumulation allowlist.  Walked per nesting level: each
    nested jaxpr runs its own pass over its own converts.  Call-like
    consumers (pjit/scan/cond/custom_jvp_call/... — anything carrying a
    subjaxpr) are transparent, not findings: the value merely crosses a
    call boundary there, and what happens to it inside is not an upcast
    hazard by itself (flagging 'consumed by scan' would make every bf16
    contract pin noise)."""
    findings = []
    for jaxpr in iter_jaxprs(closed_jaxpr.jaxpr):
        # non-transparent consumers of each var at THIS level
        consumers: dict[int, list[str]] = {}
        for eqn in jaxpr.eqns:
            if _has_subjaxpr(eqn):
                continue
            for atom in eqn.invars:
                if not _is_literal(atom):
                    consumers.setdefault(id(atom), []).append(
                        eqn.primitive.name)
        for eqn in jaxpr.eqns:
            if eqn.primitive.name != "convert_element_type":
                continue
            src = eqn.invars[0]
            if _is_literal(src):
                continue
            src_dtype = str(getattr(src.aval, "dtype", ""))
            out = eqn.outvars[0]
            out_dtype = str(getattr(out.aval, "dtype", ""))
            message = _JA002_FLOWS.get((src_dtype, out_dtype))
            if message is None:
                continue
            bad = sorted({p for p in consumers.get(id(out), ())
                          if p not in allow})
            if bad:
                shape = tuple(getattr(src.aval, "shape", ()))
                findings.append(AuditFinding(
                    "JA002", "dtype_upcast",
                    message.format(shape=list(shape),
                                   bad=", ".join(bad))))
    return findings


# ----------------------------------------------------------- output findings

def output_findings(closed_jaxpr) -> list[AuditFinding]:
    """Dead outputs (no dependence on any input — a constant the caller
    re-fetches every dispatch) and duplicate outputs (the same var
    returned twice — the trainer is keeping two names for one buffer)."""
    jaxpr = closed_jaxpr.jaxpr
    depends: dict[int, bool] = {id(v): True for v in jaxpr.invars}
    for eqn in jaxpr.eqns:
        dep = any(depends.get(id(a), False) for a in eqn.invars
                  if not _is_literal(a))
        for ov in eqn.outvars:
            depends[id(ov)] = dep
    findings = []
    seen: dict[int, int] = {}
    for i, ov in enumerate(jaxpr.outvars):
        aval = getattr(ov, "aval", None)
        desc = _format_aval(aval) if aval is not None else "<literal>"
        if _is_literal(ov) or not depends.get(id(ov), False):
            findings.append(AuditFinding(
                "JA003", "dead_output",
                f"output #{i} ({desc}) does not depend on any input — a "
                "baked constant shipped back every dispatch; drop it or "
                "compute it once on host"))
        elif id(ov) in seen:
            findings.append(AuditFinding(
                "JA004", "duplicate_output",
                f"output #{i} ({desc}) duplicates output #{seen[id(ov)]} "
                "— the same buffer returned twice costs an extra copy "
                "out of the program"))
        else:
            seen[id(ov)] = i
    return findings


# ------------------------------------------------------------ const findings

def constant_report(closed_jaxpr,
                    large_const_bytes: int = DEFAULT_LARGE_CONST_BYTES
                    ) -> tuple[dict, list[AuditFinding]]:
    import numpy as np

    total = 0
    largest = (0, "")
    n = 0
    for c in closed_jaxpr.consts:
        shape = getattr(c, "shape", ())
        dtype = getattr(c, "dtype", None)
        if dtype is not None:
            nbytes = int(np.prod(shape, dtype=np.int64)) \
                * np.dtype(dtype).itemsize
        else:
            nbytes = getattr(c, "nbytes", 0)
        total += int(nbytes)
        n += 1
        if nbytes > largest[0]:
            largest = (int(nbytes),
                       f"{np.dtype(dtype).name if dtype is not None else '?'}"
                       f"{list(shape)}")
    report = {"count": n, "total_bytes": total,
              "largest_bytes": largest[0], "largest": largest[1] or None}
    findings = []
    if total > large_const_bytes:
        findings.append(AuditFinding(
            "JA005", "large_const",
            f"{n} constant(s) totaling {total / 2**20:.1f} MiB baked into "
            f"the trace (largest: {largest[1]}, "
            f"{largest[0] / 2**20:.1f} MiB) — closure-captured arrays ride "
            "every dispatch; pass them as arguments (or accept and pin "
            "this in the program's contract)"))
    return report, findings


# --------------------------------------------------------- donation findings

def _aliased_outputs(compiled) -> int | None:
    """Input->output alias pairs in the compiled module's header
    (``input_output_alias={ {0}: (0, {}, may-alias), ... }``).  This is
    the aliasing XLA actually committed to — and unlike
    ``memory_analysis().alias_size_in_bytes`` it survives persistent-
    compile-cache deserialization, which reports zeroed memory stats."""
    try:
        text = compiled.as_text()
    except Exception:
        return None
    if not text:
        return None
    for line in text.splitlines():
        if "input_output_alias=" in line:
            return line.count("-alias)")
        if line.startswith("HloModule"):
            # entry module header without the attribute: nothing aliased
            return 0
    return 0


def donation_report(traced, compiled) -> tuple[dict, list[AuditFinding]]:
    """Declared donations (trace-level ``args_info``) vs the aliasing the
    compiled program actually committed to (the HLO module's
    ``input_output_alias`` attribute, with ``memory_analysis`` aliased
    bytes as a secondary, cache-permitting signal).  ``None`` fields mean
    the program was not compiled or the backend hides the module."""
    import jax
    import numpy as np

    declared_args = 0
    declared_bytes = 0
    if traced is not None:
        for leaf in jax.tree.leaves(traced.args_info):
            if getattr(leaf, "donated", False):
                declared_args += 1
                shape = getattr(leaf, "shape", ())
                dtype = getattr(leaf, "dtype", None)
                if dtype is not None:
                    declared_bytes += int(
                        np.prod(shape, dtype=np.int64)
                    ) * np.dtype(dtype).itemsize
    aliased = None
    alias_bytes = None
    if compiled is not None:
        aliased = _aliased_outputs(compiled)
        try:
            mem = compiled.memory_analysis()
            if mem is not None:
                alias_bytes = int(mem.alias_size_in_bytes)
        except Exception:
            alias_bytes = None
    effective = None  # nothing declared, or the module is unreadable
    if declared_args and aliased is not None:
        effective = aliased > 0
    report = {
        "declared_args": declared_args,
        "declared_bytes": int(declared_bytes),
        "aliased_outputs": aliased,
        "alias_bytes": alias_bytes,
        "effective": effective,
    }
    findings = []
    if declared_args and aliased == 0:
        findings.append(AuditFinding(
            "JA006", "donation",
            f"{declared_args} argument(s) ({declared_bytes / 2**20:.1f} "
            "MiB) declared donated but the compiled program aliased "
            "nothing — donation failed (dtype/layout mismatch between "
            "the donated input and any output?); peak HBM holds both "
            "copies"))
    return report, findings


# -------------------------------------------------------------------- driver

def _format_aval(aval) -> str:
    import numpy as np

    dtype = getattr(aval, "dtype", None)
    shape = getattr(aval, "shape", None)
    if dtype is None or shape is None:
        return str(aval)
    return f"{np.dtype(dtype).name}{list(shape)}"


def audit(fn, args: tuple = (), *, name: str = "program",
          compile: bool = True,
          f32_allow: frozenset = DEFAULT_F32_ACCUM_ALLOW,
          large_const_bytes: int = DEFAULT_LARGE_CONST_BYTES,
          overlap_expected: bool = False,
          mesh_axes: dict | None = None) -> dict:
    """Audit one jitted callable at ``args`` (concrete arrays or
    ShapeDtypeStructs — tracing never executes the program).

    ``compile=False`` stops at the jaxpr: collective/dtype/output/const
    checks only, no HLO inventory, no donation-aliasing or FLOPs fields
    (trace-only costs well under a second even for the full train step).

    ``f32_allow`` widens JA002's accumulation allowlist — a
    mixed-precision policy passes its declared accumulation points
    (``train.precision.Policy.ja002_allow``) so the bf16 step audits
    strictly against what the policy actually declared.

    ``overlap_expected`` stamps the report as one whose collectives are
    structured for comm/compute overlap (the bucketed train step);
    :mod:`contracts` turns that into a ``require_async_starts``
    expectation on platforms whose compiler lowers async collectives
    (TPU) — see ``contract_from_report``.

    ``mesh_axes`` (ordered ``{axis: size}`` of the program's mesh, e.g.
    ``{"data": 4, "model": 2}``) adds a per-mesh-axis HLO collective
    inventory under ``collectives["hlo_axes"]``
    (:func:`mesh_axis_collective_counts`) — the pin the per-strategy
    plan contracts use so a 2-D step regressing to replicated fails
    ``check`` on its vanished model-axis collectives, not on vibes.
    Reports without it keep the pre-existing two-level collectives dict,
    so older contracts stay byte-stable.

    Returns the JSON-able report :mod:`contracts` pins.  Its
    ``timing_ms`` field (``{"lower", "compile", "walk"}`` wall-clock
    millis; ``compile`` is None under ``compile=False``) attributes
    where contract-gate time goes — it rides into bench.py's
    ``ir_audit_fields`` but is never pinned by a contract.
    """
    import time

    import jax

    from ..telemetry.lowering import lower_cached

    t0 = time.perf_counter()
    prog = lower_cached(fn, *args)
    traced = prog.traced
    if traced is None:
        raise RuntimeError(
            "this jax version has no AOT fn.trace(); jaxaudit needs the "
            "ClosedJaxpr of the exact jitted callable")
    closed = traced.jaxpr
    t_lower = time.perf_counter()

    # force the (lazy, cached) executable before any walking so the
    # compile cost is attributed to itself, not to the first walk
    compiled = prog.compiled if compile else None
    t_compile = time.perf_counter()

    findings: list[AuditFinding] = []
    findings += dtype_upcast_findings(closed, allow=f32_allow)
    findings += output_findings(closed)
    consts, const_findings = constant_report(
        closed, large_const_bytes=large_const_bytes)
    findings += const_findings

    donation, donation_findings = donation_report(traced, compiled)
    findings += donation_findings

    # one line walk feeds both the aggregate per-axis counts and the
    # ordered per-axis schedule (jaxguard's JG002 substrate)
    axis_labels = None
    if mesh_axes is not None and compile:
        axis_labels = _collective_line_labels(compiled, mesh_axes)

    report = {
        "program": name,
        "platform": jax.devices()[0].platform,
        "n_devices": len(jax.devices()),
        "overlap_expected": overlap_expected,
        # "hlo_axes"/"hlo_schedule" (per-mesh-axis attribution) join the
        # dict only when the caller named the mesh (plan-built programs)
        # — absent otherwise, keeping pre-existing contracts byte-stable
        "collectives": {
            "jaxpr": collective_inventory(closed),
            "hlo": hlo_collective_counts(compiled) if compile else None,
            **({} if mesh_axes is None else {
                "hlo_axes": None if axis_labels is None
                else _counts_from_labels(axis_labels),
                "hlo_schedule": None if axis_labels is None
                else _schedule_from_labels(axis_labels)}),
        },
        "outputs": [_format_aval(getattr(v, "aval", None))
                    for v in closed.jaxpr.outvars],
        "donation": donation,
        "constants": consts,
        "flops": None,
        "bytes_accessed": None,
        "findings": [dataclasses.asdict(f) for f in findings],
        "finding_counts": {
            cls: sum(1 for f in findings if f.cls == cls)
            for cls in FINDING_CLASSES
        },
    }
    if compile:
        cost = prog.cost()
        report["flops"] = cost["flops"]
        report["bytes_accessed"] = cost["bytes"]
    t_walk = time.perf_counter()
    report["timing_ms"] = {
        "lower": round((t_lower - t0) * 1e3, 2),
        "compile": round((t_compile - t_lower) * 1e3, 2)
        if compile else None,
        "walk": round((t_walk - t_compile) * 1e3, 2),
    }
    return report


def audit_many(programs: dict, **kwargs) -> dict:
    """``{name: (fn, args)} -> {name: report}`` (see :func:`audit`).

    An entry may also be ``(fn, args, audit_kwargs)`` — per-program
    audit options (a mixed-precision program's ``f32_allow``, the
    bucketed step's ``overlap_expected``) merged over ``kwargs``."""
    reports = {}
    for nm, entry in programs.items():
        fn, args, *rest = entry
        per = dict(kwargs, **rest[0]) if rest else kwargs
        reports[nm] = audit(fn, args, name=nm, **per)
    return reports


def struct_of(tree) -> Any:
    """ShapeDtypeStruct templates of a pytree of arrays — the safe way to
    hand a donated state to :func:`audit` (tracing never executes, but a
    struct can never be consumed either)."""
    import jax

    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)
        if hasattr(x, "shape") and hasattr(x, "dtype") else x, tree)
