"""Static analysis: jaxlint (AST) + jaxaudit (IR) for TPU-hazard patterns.

The reference repo's header is a hand-maintained checklist of correctness
hazards (train_pascal.py:1-8); this framework's equivalents — silent
recompiles, host-device syncs inside the step loop, PRNG key reuse,
forgotten donation — are only observable after an expensive TPU run.  This
package catches them statically, in CI, before a chip is touched:

    python -m distributedpytorch_tpu.analysis [paths...]
    jaxlint [paths...]                       # console entry point

Rules (see :mod:`rules` and docs/DESIGN.md "Static analysis"):

===== ======================================================================
code  catches
===== ======================================================================
JL001 host-device sync inside a jitted function (.item(), float(), np.*)
JL002 recompile hazard: Python if/while on tracer-derived values in jit
JL003 PRNG discipline: key reuse without split; PRNGKey(const) in a loop
JL004 donation drift: jit of a state-updating step without donate_argnums
JL005 sharding drift: PartitionSpec axis names not defined by parallel/mesh
JL006 dtype leak: float64 flowing into device code (jnp.float64, x64 flag)
JL007 leftover debug statements (jax.debug.print, breakpoint, print-in-jit)
JL008 jnp.array/asarray without explicit dtype in jit (silent f32 upcast)
JL000 meta: unknown rule code inside a ``# jaxlint: disable=`` comment
===== ======================================================================

Suppression: ``# jaxlint: disable=JL001`` on the offending line, or
``# jaxlint: disable-file=JL001`` anywhere in the file for a file-wide
waiver.  Runtime complement: :class:`utils.compile_watchdog.CompileWatchdog`
counts actual XLA compilations and fails tests that recompile steady-state
steps.

Between the per-file syntax layer and the per-program IR layer sits
**jaxguard** (:mod:`spmd` + :mod:`donation` + :mod:`guard`): dataflow
across statements and comparison across programs — host-divergence
taint into collective-issuing control flow (JG001), ordered per-axis
collective schedules cross-checked pairwise over the plan ladder
(JG002, the static multi-host deadlock detector), and donation aliasing
across the trace boundary (JG003 use-after-donate, JG004 zero-copy
donation — the PR 5/PR 6 bug class):

    python -m distributedpytorch_tpu.analysis --guard check
    jaxaudit --guard check                   # same entry point

Its AST half is import-light like jaxlint (``--no-ir`` for pre-commit);
suppressions use ``# jaxguard: disable=JG00x`` and are policed for
staleness by ``jaxlint --stats`` alongside jaxlint's own.

The fourth layer, **jaxrace** (:mod:`race` + :mod:`threadsan`), leaves
the device entirely: the serve stack is a multi-threaded HOST program
(submit threads, a worker, a swap admitting new generations, signal
handlers), and its hazards — unguarded shared state, lock-order
inversions, blocking calls in signal handlers or under locks — are
invisible to all jax-level layers.  jaxrace builds a thread model per
class (locks, guarded attributes via ``# jaxrace: guarded-by=...``
declarations or majority inference, lock acquisition order) and judges
it flow-sensitively (JR001–JR004), pinning the guard map and blessed
lock order in ``tests/contracts/threads.json``:

    python -m distributedpytorch_tpu.analysis --race check
    jaxrace check                            # console entry point

Its runtime witness, :mod:`threadsan` (``DPTPU_THREADSAN=1``), wraps
the declared locks and instruments attribute writes so the existing
under-load serve/swap tests validate the static guard map against real
thread schedules.  Suppressions use ``# jaxrace: disable=JR00x`` and
are policed for staleness by ``jaxlint --stats`` like the others.

The hazards the AST structurally cannot see — they exist only in the
traced jaxpr and the compiled HLO — are jaxaudit's job (:mod:`ir` +
:mod:`contracts`, docs/DESIGN.md "IR auditing & compile contracts"):

    python -m distributedpytorch_tpu.analysis --ir check
    jaxaudit check                           # console entry point

jaxaudit traces the REAL train/eval/serve programs, inventories their
collectives per mesh axis, checks dtype flow (JA002), dead/duplicate
outputs (JA003/JA004), baked constants (JA005) and donation aliasing
(JA006), and diffs everything against platform-keyed compile contracts
checked in under ``tests/contracts/``.  ``ir``/``contracts`` import jax;
they are deliberately NOT imported here so the linter half stays usable
in editors and pre-commit hooks with no backend.
"""

from .core import (
    Finding,
    RULES,
    lint_paths,
    lint_source,
    main,
    suppression_report,
)
from . import rules as _rules  # noqa: F401  populates RULES at import
from .guard import GUARD_RULES, guard_paths, guard_source
from .race import RACE_RULES, race_paths, race_source

__all__ = ["Finding", "RULES", "GUARD_RULES", "RACE_RULES", "lint_paths",
           "lint_source", "guard_paths", "guard_source", "race_paths",
           "race_source", "suppression_report", "main"]
