"""jaxguard driver: the middle static-analysis layer, between jaxlint
(per-file AST) and jaxaudit (per-program IR).

What each layer can and cannot see:

* **jaxlint** reads one file's syntax — it catches a ``time.time()``
  inside a jit body, but not a host-divergent *decision* three
  statements away from the collective it gates;
* **jaxguard** (this module) reads dataflow across statements and
  *compares programs against each other* — host-divergence taint into
  collective-issuing control flow (JG001, :mod:`spmd`), ordered
  per-mesh-axis collective schedules cross-checked pairwise over the
  plan ladder's programs (JG002, the static deadlock detector), and
  donation aliasing across the trace boundary (JG003/JG004,
  :mod:`donation`);
* **jaxaudit** pins what one program compiled to.

Rules:

====== ========================== =========================================
JG000  meta                       syntax error / malformed or typo'd
                                  ``# jaxguard:`` suppression comment
JG001  host-divergent collective  collective-issuing call under control
                                  flow tainted by a host-divergent source
JG002  schedule divergence        two programs sharing a mesh axis issue
                                  different ordered collective sequences
JG003  use-after-donate           a binding read after being passed in a
                                  donated position
JG004  zero-copy donation         host-numpy-backed value donated without
                                  an interposed ``jnp.copy``
====== ========================== =========================================

Suppressions use the jaxlint grammar with the jaxguard prefix
(``# jaxguard: disable=JG003``); ``jaxlint --stats`` polices both tools'
directives for staleness.

The AST half (``guard_paths``) is import-light — stdlib only, safe for
pre-commit.  The IR half (``--guard check`` without ``--no-ir``)
compiles the plan ladder's programs on the canonical pinned topology and
cross-checks their schedules against the checked-in
``tests/contracts/guard_schedules.<key>.json`` pin.
"""

from __future__ import annotations

import ast
import itertools
import json
import os
import sys

from .core import Finding, iter_python_files, parse_suppressions
from .donation import find_donation_hazards
from .spmd import (
    _first_mismatch,
    find_host_divergence,
    schedule_divergence,
    stale_divergence_declarations,
)

META_CODE = "JG000"

#: code -> (name, summary); JG002 is IR-side (it needs compiled
#: programs), the rest are AST-side
GUARD_RULES = {
    "JG001": ("host-divergent-collective",
              "collective-issuing call gated by host-divergent control "
              "flow (time/env/random/process_index/fs/HBM probes) — "
              "silent multi-host deadlock; launder the decision through "
              "parallel/consensus.replicated_decision"),
    "JG002": ("schedule-divergence",
              "programs sharing a mesh axis issue different ordered "
              "collective sequences (IR-side: `--guard check`) — "
              "alternates of one dispatch point must be lockstep or "
              "declared divergent in the guard schedule contract"),
    "JG003": ("use-after-donate",
              "binding read after being passed in a donate_argnums "
              "position — the buffer may already be reused; rebind "
              "through the call or pass a copy"),
    "JG004": ("zero-copy-donation",
              "host-numpy-backed value (np.* / device_put of it) flows "
              "into a donated argument without an interposed jnp.copy — "
              "the PR 5 Orbax-restore segfault / PR 6 warm-start NaN "
              "class"),
}

GUARD_CODES = frozenset(GUARD_RULES) | {META_CODE}

#: the checked-in cross-program schedule pin (kind "schedule_set")
SCHEDULE_SET_NAME = "guard_schedules"


# ------------------------------------------------------------- the AST half

def guard_source(src: str, path: str = "<string>",
                 tree: ast.AST | None = None,
                 suppress: bool = True) -> list[Finding]:
    """Run the AST-side jaxguard passes (JG001, JG003, JG004) over one
    source string.  ``suppress=False`` ignores ``# jaxguard:`` disable
    comments (the raw view :func:`core.suppression_report` audits)."""
    if tree is None:
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            return [Finding(META_CODE, f"syntax error: {e.msg}", path,
                            e.lineno or 1, e.offset or 0)]
    findings = find_host_divergence(tree, path)
    findings += find_donation_hazards(tree, path)
    line_dis, file_dis, meta = parse_suppressions(
        src, path, set(GUARD_CODES), tool="jaxguard",
        meta_code=META_CODE)
    if not suppress:
        line_dis, file_dis = {}, set()
    findings = [
        f for f in findings
        if f.code not in file_dis
        and f.code not in line_dis.get(f.line, ())
    ]
    findings.extend(m for m in meta
                    if m.code not in file_dis
                    and m.code not in line_dis.get(m.line, ()))
    return sorted(findings, key=lambda f: (f.line, f.col, f.code))


def guard_paths(paths) -> list[Finding]:
    """AST-side jaxguard over files/trees, sorted by position."""
    findings: list[Finding] = []
    for f in iter_python_files(paths):
        with open(f, encoding="utf-8") as fh:
            src = fh.read()
        findings.extend(guard_source(src, path=f))
    return sorted(findings, key=lambda x: (x.path, x.line, x.col, x.code))


# -------------------------------------------------------------- the IR half

def extract_schedules(programs: dict) -> dict:
    """``{name: {axis: [rle ops...]}}`` for every program whose audit
    kwargs name a mesh (``mesh_axes``) — lowered and compiled through
    the process-wide cache, but NOT fully audited: the schedule walk is
    the only thing this gate needs."""
    from ..telemetry.lowering import lower_cached
    from .ir import mesh_axis_collective_schedule

    schedules: dict = {}
    for name, entry in programs.items():
        fn, args, *rest = entry
        kw = rest[0] if rest else {}
        mesh_axes = kw.get("mesh_axes")
        if not mesh_axes:
            continue
        prog = lower_cached(fn, *args)
        sched = mesh_axis_collective_schedule(prog.compiled, mesh_axes)
        if sched is not None:
            schedules[name] = sched
    return schedules


def schedule_pin_path(contracts_dir: str, key: str) -> str:
    return os.path.join(contracts_dir, f"{SCHEDULE_SET_NAME}.{key}.json")


def load_schedule_set(contracts_dir: str, key: str) -> dict | None:
    path = schedule_pin_path(contracts_dir, key)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def divergent_pairs_of(schedules: dict) -> list:
    """The pairs that genuinely diverge today — what ``--guard update``
    auto-declares, so ``check`` then polices that the set neither grows
    (an undeclared divergence is JG002) nor shrinks (a stale
    declaration)."""
    out = []
    for a, b in itertools.combinations(sorted(schedules), 2):
        shared = set(schedules[a]) & set(schedules[b])
        if any(schedules[a][ax] != schedules[b][ax] for ax in shared):
            out.append([a, b])
    return out


def save_schedule_set(schedules: dict, contracts_dir: str,
                      key: str) -> str:
    os.makedirs(contracts_dir, exist_ok=True)
    doc = {
        "kind": "schedule_set",
        "program": SCHEDULE_SET_NAME,
        "platform_key": key,
        "schedules": schedules,
        "divergent_pairs": divergent_pairs_of(schedules),
    }
    path = schedule_pin_path(contracts_dir, key)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def diff_schedule_set(pinned: dict, schedules: dict) -> list[str]:
    """Per-program drift of the live schedules against the pin — a
    reordering that JG002 alone cannot see when every program moved in
    lockstep (pairwise comparison stays equal; the pin does not)."""
    drift: list[str] = []
    want = pinned.get("schedules") or {}
    for name in sorted(set(want) | set(schedules)):
        if name not in schedules:
            drift.append(f"{name}: pinned but no longer built — run "
                         "`--guard update`")
            continue
        if name not in want:
            drift.append(f"{name}: live program has no pinned schedule "
                         "— run `--guard update` and review")
            continue
        w, h = want[name], schedules[name]
        for ax in sorted(set(w) | set(h)):
            if ax not in h:
                drift.append(f"{name}: axis {ax!r} vanished from the "
                             f"live schedule (pinned {w[ax]})")
            elif ax not in w:
                drift.append(f"{name}: live schedule gained axis "
                             f"{ax!r} ({h[ax]}) — not pinned")
            elif w[ax] != h[ax]:
                drift.append(
                    f"{name}: schedule[{ax}] reordered — "
                    f"{_first_mismatch(w[ax], h[ax])} "
                    "(pinned vs live)")
    return drift


def check_schedules(schedules: dict, contracts_dir: str,
                    key: str) -> list[str]:
    """The full IR-side gate: pin drift + undeclared pairwise
    divergence (JG002) + stale divergence declarations.  Returns
    human-readable failure lines; empty == green."""
    pinned = load_schedule_set(contracts_dir, key)
    if pinned is None:
        return [f"no schedule pin "
                f"{SCHEDULE_SET_NAME}.{key}.json in {contracts_dir} — "
                "run `--guard update` and review the pins"]
    declared = pinned.get("divergent_pairs") or []
    failures = diff_schedule_set(pinned, schedules)
    failures += [f.format() for f in
                 schedule_divergence(schedules, declared)]
    failures += stale_divergence_declarations(schedules, declared)
    return failures


# ------------------------------------------------------------------- the CLI

def run_guard_cli(argv: list[str] | None = None,
                  programs: dict | None = None) -> int:
    """``jaxaudit --guard {audit|check|update|list} [paths...]``.

    * ``audit``  — print AST findings and live schedules (informational,
      exit 0);
    * ``check``  — the gate: AST findings or schedule drift/divergence
      exit 1.  ``--no-ir`` skips the compile half (fast pre-commit);
    * ``update`` — regenerate the schedule pin after a REVIEWED change;
    * ``list``   — the rule table.

    ``programs`` injects a prebuilt ``{name: (fn, args, kwargs)}``
    registry (same shape as :func:`contracts.build_default_programs`);
    tests guard throwaway jits through the same code path the gate runs.
    """
    import argparse

    parser = argparse.ArgumentParser(
        prog="jaxguard",
        description="cross-program SPMD-divergence + donation-safety "
                    "analyzer (see docs/DESIGN.md 'Static analysis').")
    parser.add_argument("command",
                        choices=("audit", "check", "update", "list"),
                        help="audit: print findings+schedules; check: "
                             "gate (exit 1 on findings/drift); update: "
                             "regenerate schedule pins; list: rules")
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    parser.add_argument("paths", nargs="*", default=[pkg_dir],
                        help="files or directories for the AST half "
                             "(default: the package)")
    parser.add_argument("--no-ir", action="store_true",
                        help="skip the IR half (no jax import, no "
                             "compiles) — pre-commit speed")
    parser.add_argument("--programs",
                        help="comma-separated program subset for the IR "
                             "half (default: the plan ladder)")
    parser.add_argument("--contracts-dir", default=None,
                        help="contract directory (default: the repo's "
                             "tests/contracts)")
    # intermixed: `check --no-ir path1 path2` — plain parse_args can't
    # resume a nargs="*" positional after an optional
    args = parser.parse_intermixed_args(argv)

    if args.command == "list":
        print(f"{META_CODE}  meta: syntax error or malformed/unknown "
              "# jaxguard: suppression")
        for code in sorted(GUARD_RULES):
            name, summary = GUARD_RULES[code]
            print(f"{code}  {name}: {summary}")
        return 0

    findings = guard_paths(args.paths)
    for f in findings:
        print(f.format())

    if args.command == "check" and args.no_ir:
        if findings:
            print(f"jaxguard: {len(findings)} finding(s)",
                  file=sys.stderr)
            return 1
        return 0

    if args.no_ir:
        return 0

    # ---- IR half ----
    from .contracts import (
        PLAN_PROGRAM_NAMES,
        _pin_cpu_topology,
        build_default_programs,
        default_contracts_dir,
        platform_key,
    )

    names = tuple(s.strip() for s in args.programs.split(",")
                  if s.strip()) if args.programs else None
    contracts_dir = args.contracts_dir or default_contracts_dir()
    if programs is None:
        _pin_cpu_topology()
        try:
            from ..backend_health import enable_compile_cache

            enable_compile_cache()
        except Exception:
            pass
        try:
            programs = build_default_programs(names or PLAN_PROGRAM_NAMES)
        except ValueError as e:
            print(f"jaxguard: error: {e}", file=sys.stderr)
            return 2
    elif names:
        unknown = set(names) - set(programs)
        if unknown:
            print(f"jaxguard: error: unknown program(s) "
                  f"{sorted(unknown)}", file=sys.stderr)
            return 2
        programs = {n: programs[n] for n in names}

    schedules = extract_schedules(programs)
    key = platform_key()

    if args.command == "audit":
        print(json.dumps(schedules, indent=1, sort_keys=True))
        if findings:
            print(f"jaxguard: {len(findings)} finding(s)",
                  file=sys.stderr)
        return 0

    if args.command == "update":
        path = save_schedule_set(schedules, contracts_dir, key)
        print(f"wrote {path}")
        return 0

    # check
    failures = check_schedules(schedules, contracts_dir, key)
    for line in failures:
        print(line)
    if not failures:
        print(f"guard_schedules: ok ({key}, "
              f"{len(schedules)} program(s))")
    if findings or failures:
        print(f"jaxguard: {len(findings)} finding(s), "
              f"{len(failures)} schedule failure(s)", file=sys.stderr)
        return 1
    return 0
