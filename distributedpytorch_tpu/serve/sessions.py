"""Session-affine feature cache: pay the backbone once per image, not per click.

The DEXTR workload is *interactive*: a user places extreme points, gets a
mask, and refines it with further clicks on the SAME image.  With a split
predictor (``predict.Predictor.supports_sessions`` — guidance_inject='head'
models) the backbone+attention encoding of the session's crop is a pure
function of the image, so it is computed once on the first (cold) click and
cached ON DEVICE; every refinement (warm) click re-synthesizes only the
guidance channel and pays a ``decode`` — the FFCV principle ("never
recompute what is deterministic across iterations", PAPERS.md 2306.12517)
applied to inference.

This module is the pure store; the queueing/dispatch policy lives in
:class:`service.InferenceService`.  What the store owns:

* **Device-resident entries.**  ``Session.features`` is the encoded
  (1, H/os, W/os, C) feature map, kept as a device array — a cache that
  round-trips features through host numpy would pay two PCIe copies per
  warm click and erase most of the win.
* **An explicit HBM byte budget.**  Features are HBM; an unbounded cache
  is an OOM with a delay.  ``put`` evicts least-recently-used entries
  until the new entry fits (the budget bounds resident bytes at
  ``max(budget_bytes, one entry)`` — a store that refused oversized
  entries could never serve large-crop sessions at all).  The eviction
  math, concretely: one 512² os=8 ResNet-101 session is
  64·64·2048·4 B = 32 MiB, so a 2 GiB budget holds 64 live sessions; the
  64px ResNet-18 test config is 8·8·512·4 B = 128 KiB per session.
* **TTL expiry.**  Abandoned sessions (the user closed the tab) expire
  ``ttl_s`` after their last use — reaped lazily on access and by the
  service worker's periodic :meth:`sweep`.
* **Generation affinity.**  Features encoded by params generation N are
  only decodable by generation N (serve/swap.py); entries record their
  generation so a hot-swap can pin old params until their last session
  drains, and a rollback can evict exactly the canary's sessions.

Observability rides the process-wide telemetry registry:
``serve_session_live_bytes`` / ``serve_sessions_live`` gauges,
``serve_session_evictions_total{reason=ttl|lru|explicit|generation}``,
``serve_session_hits_total`` / ``serve_session_misses_total`` counters.
"""

from __future__ import annotations

import collections
import threading
import time
import zlib

import numpy as np

from ..telemetry.registry import MetricsRegistry, get_registry

#: eviction reasons — the counter's closed label set
EVICT_REASONS = ("ttl", "lru", "explicit", "generation")


def image_digest(image) -> int:
    """Cheap identity fingerprint of the full image (crc32 of the raw
    bytes + shape) — computed once per click on the submitting thread
    (~100µs at 512²) so a reused session id with a DIFFERENT image of
    the same size re-encodes instead of decoding the old image's
    features."""
    arr = np.ascontiguousarray(np.asarray(image))
    return zlib.crc32(arr.tobytes()) ^ hash(arr.shape) & 0xFFFFFFFF


class Session:
    """One live interactive session: the cached encoding + its crop frame."""

    __slots__ = ("session_id", "features", "bbox", "shape_hw", "generation",
                 "nbytes", "created", "last_used", "clicks", "digest")

    def __init__(self, session_id: str, features, bbox, shape_hw,
                 generation: int, now: float, digest: int = 0):
        self.session_id = session_id
        self.features = features
        self.bbox = tuple(int(v) for v in bbox)
        self.shape_hw = tuple(int(v) for v in shape_hw)
        self.generation = int(generation)
        self.nbytes = int(np.prod(features.shape)
                          * np.dtype(features.dtype).itemsize)
        self.created = now
        self.last_used = now
        self.clicks = 1
        self.digest = int(digest)

    def covers(self, points, shape_hw, digest: int | None = None) -> bool:
        """Can a refinement click reuse this entry?  The clicks must fall
        inside the session's established crop (guidance is synthesized in
        that crop's coordinates) and the image must be THE image the
        features encode (size + content fingerprint) — a different image
        under a reused session id is a client bug that must degrade to a
        re-encode, never to a mask from the wrong image's features."""
        if tuple(int(v) for v in shape_hw) != self.shape_hw:
            return False
        if digest is not None and digest != self.digest:
            return False
        pts = np.asarray(points, np.float64)
        x0, y0, x1, y1 = self.bbox
        return bool((pts[:, 0] >= x0).all() and (pts[:, 0] <= x1).all()
                    and (pts[:, 1] >= y0).all() and (pts[:, 1] <= y1).all())


class SessionStore:
    """TTL + LRU session cache under an explicit device-byte budget.

    Thread-safe: the service's submit path (many client threads) and the
    worker share it.  All mutation happens under one lock; the stored
    feature arrays themselves are immutable device buffers.
    """

    def __init__(self, budget_bytes: int = 256 << 20, ttl_s: float = 600.0,
                 registry: MetricsRegistry | None = None):
        if budget_bytes < 1:
            raise ValueError(f"budget_bytes must be >= 1, got {budget_bytes}")
        if ttl_s <= 0:
            raise ValueError(f"ttl_s must be > 0, got {ttl_s}")
        self.budget_bytes = int(budget_bytes)
        self.ttl_s = float(ttl_s)
        self._lock = threading.Lock()
        #: insertion/use order IS the LRU order (move_to_end on touch)
        self._entries: collections.OrderedDict[str, Session] = \
            collections.OrderedDict()
        self._live_bytes = 0
        reg = registry or get_registry()
        self._g_bytes = reg.gauge(
            "serve_session_live_bytes",
            "device bytes held by cached session encodings")
        self._g_live = reg.gauge(
            "serve_sessions_live", "live interactive sessions")
        self._c_evict = {
            reason: reg.counter(
                "serve_session_evictions_total",
                "session-cache evictions", labels={"reason": reason})
            for reason in EVICT_REASONS}
        self._c_hit = reg.counter(
            "serve_session_hits_total",
            "warm clicks served from the feature cache")
        self._c_miss = reg.counter(
            "serve_session_misses_total",
            "clicks that had to (re-)encode (new/expired/out-of-crop)")
        #: registry values at store construction — the registry keeps
        #: process-lifetime totals; this store reports ITS OWN deltas
        #: (the ServeMetrics baseline convention)
        self._base = {
            "hits": self._c_hit.value, "misses": self._c_miss.value,
            **{f"evict_{r}": c.value for r, c in self._c_evict.items()}}

    # ------------------------------------------------------------- accessors

    def get(self, session_id: str, now: float | None = None
            ) -> Session | None:
        """The live entry (LRU-touched), or None (expired entries are
        reaped here).  Hit/miss accounting is the CALLER's move
        (:meth:`hit`/:meth:`miss`) — a miss by coverage happens after a
        successful get."""
        now = time.monotonic() if now is None else now
        with self._lock:
            sess = self._entries.get(session_id)
            if sess is None:
                return None
            if now - sess.last_used > self.ttl_s:
                self._drop(session_id, "ttl")
                return None
            sess.last_used = now
            self._entries.move_to_end(session_id)
            return sess

    def hit(self) -> None:
        self._c_hit.inc()

    def miss(self) -> None:
        self._c_miss.inc()

    # -------------------------------------------------------------- mutation

    def put(self, session_id: str, features, bbox, shape_hw,
            generation: int, now: float | None = None,
            digest: int = 0) -> Session:
        """Install/replace an entry, evicting LRU until it fits the
        budget.  The NEW entry is always admitted (see module doc)."""
        now = time.monotonic() if now is None else now
        sess = Session(session_id, features, bbox, shape_hw, generation,
                       now, digest=digest)
        with self._lock:
            if session_id in self._entries:
                self._drop(session_id, "explicit")
            while (self._entries
                   and self._live_bytes + sess.nbytes > self.budget_bytes):
                oldest = next(iter(self._entries))
                self._drop(oldest, "lru")
            self._entries[session_id] = sess
            self._live_bytes += sess.nbytes
            self._publish()
            return sess

    def touch_click(self, sess: Session) -> None:
        with self._lock:
            sess.clicks += 1

    def sweep(self, now: float | None = None) -> int:
        """Reap every TTL-expired entry; returns how many went."""
        now = time.monotonic() if now is None else now
        with self._lock:
            expired = [sid for sid, s in self._entries.items()
                       if now - s.last_used > self.ttl_s]
            for sid in expired:
                self._drop(sid, "ttl")
            return len(expired)

    def evict(self, session_id: str, reason: str = "explicit") -> bool:
        with self._lock:
            if session_id not in self._entries:
                return False
            self._drop(session_id, reason)
            return True

    def evict_generation(self, generation: int) -> int:
        """Drop every session bound to ``generation`` (hot-swap rollback:
        canary features must never outlive the canary params)."""
        with self._lock:
            doomed = [sid for sid, s in self._entries.items()
                      if s.generation == generation]
            for sid in doomed:
                self._drop(sid, "generation")
            return len(doomed)

    def clear(self) -> int:
        with self._lock:
            n = len(self._entries)
            for sid in list(self._entries):
                self._drop(sid, "explicit")
            return n

    # ------------------------------------------------------------------ ops

    @property
    def live_bytes(self) -> int:
        with self._lock:
            return self._live_bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def counts_by_generation(self) -> dict[int, int]:
        with self._lock:
            out: dict[int, int] = {}
            for s in self._entries.values():
                out[s.generation] = out.get(s.generation, 0) + 1
            return out

    def snapshot(self) -> dict:
        """One dict for /healthz, /stats and the sessions bench."""
        with self._lock:
            return {
                "live": len(self._entries),
                "live_bytes": self._live_bytes,
                "budget_bytes": self.budget_bytes,
                "ttl_s": self.ttl_s,
                "by_generation": {
                    str(g): n
                    for g, n in sorted(collections.Counter(
                        s.generation
                        for s in self._entries.values()).items())},
                "evictions": {
                    r: int(c.value - self._base[f"evict_{r}"])
                    for r, c in self._c_evict.items()},
                "hits": int(self._c_hit.value - self._base["hits"]),
                "misses": int(self._c_miss.value - self._base["misses"]),
            }

    # ------------------------------------------------------------- internals

    def _drop(self, session_id: str, reason: str) -> None:
        """Remove one entry; caller holds the lock."""
        sess = self._entries.pop(session_id)
        self._live_bytes -= sess.nbytes
        self._c_evict[reason].inc()
        self._publish()

    def _publish(self) -> None:
        self._g_bytes.set(float(self._live_bytes))
        self._g_live.set(float(len(self._entries)))
