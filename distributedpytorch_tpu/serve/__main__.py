"""HTTP front end: ``python -m distributedpytorch_tpu.serve --run-dir RUN``.

A thin, dependency-free (stdlib ``http.server``) shell around
:class:`service.InferenceService`: each HTTP request thread submits into
the shared bounded queue and blocks on its future, so concurrent clients
feed the micro-batcher exactly like in-process threads do.  The endpoints:

    POST /v1/predict   {"image": <wire array>, "points": [[x,y]*4],
                        "deadline_ms": optional}
                    -> {"mask": <wire array>, "latency_ms": ...}
                       429 shed (queue full) | 504 deadline | 400 bad input
    GET  /healthz   -> 200/503 liveness: service state + an in-process
                       device-op probe (backend_health.device_op_alive,
                       TTL-cached so probes stay cheap)
    GET  /stats     -> metrics snapshot (counters, p50/p99, buckets)
    GET  /metrics   -> Prometheus text exposition of the process-wide
                       telemetry registry (serve counters, span
                       percentiles, goodput gauges when co-hosted)
    POST /debug/trace?steps=N
                    -> arm a bounded on-demand jax.profiler capture of
                       the next N batches (202 + target dir; 409 when a
                       capture is already armed/active).  SIGUSR2 arms
                       the same default capture.

Wire arrays are ``{"shape", "dtype", "b64"}`` (client.py) — no pickle.
Graceful stop: SIGTERM/SIGINT land the in-flight batch, fail the queued
remainder loudly, and exit 0 (the same manners as the trainer's
preemption path).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time
from concurrent.futures import TimeoutError as FuturesTimeoutError
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from ..telemetry import get_registry, prometheus
from ..telemetry.trace import query_steps
from .client import HealthCache, decode_array, encode_array
from .service import (
    DeadlineExceededError,
    InferenceService,
    QueueFullError,
    ServiceUnhealthyError,
    SessionLaneFullError,
    warmup_buckets,  # noqa: F401  re-export; pre-consolidation import site
)

#: back-compat alias (the cache moved to client.py so the in-process
#: ServeClient path shares it)
_HealthCache = HealthCache


class _Server(ThreadingHTTPServer):
    """ThreadingHTTPServer with NON-daemon handler threads: a graceful
    stop must let handlers woken by ``service.stop()`` (their futures just
    resolved to 503s) finish WRITING those replies — daemon threads would
    be killed at interpreter exit mid-write and the queued clients would
    see a connection reset instead of the promised loud failure.
    ``server_close`` (ThreadingMixIn, block_on_close) joins them."""
    daemon_threads = False


def make_handler(service: InferenceService, health_cache: _HealthCache,
                 request_timeout_s: float = 120.0) -> type:
    """Build the request-handler class closed over the shared service.

    ``request_timeout_s`` bounds how long a handler thread waits on its
    future when the request carries no deadline: with a wedged backend the
    worker never resolves anything, and an unbounded ``result()`` would
    accumulate blocked HTTP threads forever while /healthz correctly
    reports the backend dead."""

    class Handler(BaseHTTPRequestHandler):
        # per-request threads come from ThreadingHTTPServer
        protocol_version = "HTTP/1.1"
        # headers and body flush as two unbuffered writes; on a
        # keep-alive connection (the fleet proxy pools these) Nagle
        # holds the body segment behind the peer's delayed ACK —
        # a flat ~40ms tax on every proxied reply
        disable_nagle_algorithm = True
        # buffer the reply so headers + body leave as ONE segment —
        # handle_one_request() flushes after every request, so this
        # only coalesces writes, it never delays them
        wbufsize = 64 * 1024
        # idle keep-alive bound: handler threads are NON-daemon (_Server),
        # so a connection-reusing client parked between requests would
        # otherwise block server_close()'s join forever at shutdown —
        # the socket read times out, close_connection ends the thread
        timeout = 10.0

        def log_message(self, fmt, *args):  # quiet: metrics are the log
            pass

        def _reply(self, code: int, payload: dict) -> None:
            self._reply_text(code, json.dumps(payload), "application/json")

        def _reply_text(self, code: int, text: str,
                        content_type: str) -> None:
            body = text.encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            if code == 429:
                self.send_header("Retry-After", "1")
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self) -> None:  # noqa: N802 — http.server's contract
            if self.path == "/metrics":
                # the one telemetry surface: serve counters AND any train
                # goodput/span metrics living in this process's registry
                self._reply_text(200, prometheus.render_text(get_registry()),
                                 prometheus.CONTENT_TYPE)
            elif self.path == "/healthz":
                alive, why = health_cache.probe()
                health = service.health()
                health["backend_alive"] = alive
                if not alive:
                    health["ok"] = False
                    health["unhealthy_reason"] = (
                        health.get("unhealthy_reason") or why)
                self._reply(200 if health["ok"] else 503, health)
            elif self.path == "/stats":
                self._reply(200, service.metrics.snapshot())
            else:
                self._reply(404, {"error": f"no such path {self.path!r}"})

        def do_POST(self) -> None:  # noqa: N802
            # body read isolated from the predict phase: a client stalling
            # mid-body raises the socket timeout (builtin TimeoutError on
            # 3.11+, where concurrent.futures.TimeoutError is the SAME
            # class — it must not masquerade as a 503 'backend wedged'),
            # and the desynced keep-alive stream can only be dropped
            try:
                raw = self.rfile.read(
                    int(self.headers.get("Content-Length", 0)))
            except (TimeoutError, OSError):
                self.close_connection = True
                return
            base, _, query = self.path.partition("?")
            if base == "/debug/trace":
                trig = service.trace
                if trig is None:
                    self._reply(503, {"error": "trace capture not armed "
                                               "for this service"})
                    return
                target = trig.request(query_steps(query))
                if target is None:
                    self._reply(409, {"error": "a trace capture is "
                                               "already armed or active"})
                else:
                    self._reply(202, {"trace_dir": target,
                                      "note": "starts at the next batch; "
                                              "bounded by steps and a "
                                              "wall-clock backstop"})
                return
            if base != "/v1/predict":
                # body already drained: on a keep-alive (HTTP/1.1)
                # connection unread bytes would be parsed as the client's
                # NEXT request line
                self._reply(404, {"error": f"no such path {self.path!r}"})
                return
            try:
                body = json.loads(raw.decode("utf-8"))
                image = decode_array(body["image"])
                points = np.asarray(body["points"], np.float64)
                deadline_ms = body.get("deadline_ms")
                deadline_s = None if deadline_ms is None \
                    else float(deadline_ms) / 1e3
                # session-affine serving: absent session_id (the
                # pre-session wire) stays the stateless path
                session_id = body.get("session_id")
                if session_id is not None:
                    session_id = str(session_id)
                t0 = time.perf_counter()
                fut = service.submit(image, points, deadline_s=deadline_s,
                                     session_id=session_id)
                # a request with a deadline can't legitimately outwait it
                # (+grace for the drain-side check to answer first), and
                # nobody outwaits the server-side cap — a huge client
                # deadline must not park this thread on a wedged backend
                mask = fut.result(timeout=request_timeout_s
                                  if deadline_s is None
                                  else min(deadline_s + 5.0,
                                           request_timeout_s))
                self._reply(200, {
                    "mask": encode_array(mask),
                    "latency_ms": round(
                        (time.perf_counter() - t0) * 1e3, 3)})
            except SessionLaneFullError as e:
                # same 429 + Retry-After as a queue-full shed, but a
                # distinct `code` so the client round-trips the type:
                # only the offending session should back off
                self._reply(429, {"error": str(e), "code": "session_lane"})
            except QueueFullError as e:
                self._reply(429, {"error": str(e)})
            except DeadlineExceededError as e:
                self._reply(504, {"error": str(e)})
            except FuturesTimeoutError:
                self._reply(503, {"error": (
                    "no result within the server-side wait bound — the "
                    "backend may be wedged; check /healthz")})
            except ServiceUnhealthyError as e:
                self._reply(503, {"error": str(e)})
            except (KeyError, TypeError, ValueError) as e:
                self._reply(400, {"error": f"bad request: {e}"})

    return Handler


def build_predictor(args):
    """Predictor from a run dir or a torch checkpoint — the same two
    sources the --predict CLI serves, minus the per-call restore cost.

    Quantization (``serve/quantize``): ``--quantize int8`` — or, when
    the flag is absent, the run config's ``model.quantization`` knob —
    rebuilds the restored weights as per-channel int8 + scales before
    any program compiles (``--quantize none`` overrides a config knob
    off).  Shared with ``dptpu-aot`` so the pre-compiled ladder is the
    exact ladder this boot serves."""
    from ..predict import Predictor, load_run_config

    quantize = getattr(args, "quantize", None)
    if args.run_dir:
        cfg = load_run_config(args.run_dir)
        if quantize is None:
            quantize = getattr(cfg.model, "quantization", "") or None
        predictor = Predictor.from_run(args.run_dir, cfg=cfg)
    elif getattr(args, "fresh_init", None):
        predictor = build_fresh_predictor(args.fresh_init)
    else:
        predictor = Predictor.from_torch(args.torch)
    from .quantize import quant_policy, quantize_predictor

    policy = quant_policy(quantize)
    if policy is not None:
        predictor = quantize_predictor(predictor, policy)
    return predictor


def build_fresh_predictor(spec: str):
    """Fresh-init predictor from a ``SIZE[:BACKBONE[:INJECT]]`` spec
    (default ``64:resnet18:head``) — a replica with no checkpoint at
    all, for the fleet's chaos scenarios and dev loops where the test
    is the SERVING MACHINERY (routing, membership, failover), not the
    weights.  Rides the persistent compile cache so a scenario spawning
    the same fresh replicas run after run pays the compile ladder
    once."""
    from ..backend_health import enable_compile_cache

    enable_compile_cache()
    import jax
    import optax

    from ..models import build_model
    from ..parallel import create_train_state
    from ..predict import Predictor

    parts = (spec or "64").split(":")
    size = int(parts[0] or 64)
    backbone = parts[1] if len(parts) > 1 and parts[1] else "resnet18"
    inject = parts[2] if len(parts) > 2 and parts[2] else "head"
    model = build_model("danet", nclass=1, backbone=backbone,
                        output_stride=8, guidance_inject=inject)
    state = create_train_state(jax.random.PRNGKey(0), model,
                               optax.sgd(1e-3), (1, size, size, 4))
    return Predictor(model, state.params, state.batch_stats,
                     resolution=(size, size), relax=10)


def main(argv: list[str] | None = None) -> int:
    from ..backend_health import pin_requested_platform

    pin_requested_platform()
    parser = argparse.ArgumentParser(
        prog="distributedpytorch_tpu.serve",
        description="TPU-native batched inference service for click-guided "
                    "segmentation")
    src = parser.add_mutually_exclusive_group(required=True)
    src.add_argument("--run-dir",
                     help="training run dir (config.json + checkpoints/)")
    src.add_argument("--torch", metavar="PTH",
                     help="torch state_dict checkpoint (reference "
                          "architecture) instead of a run dir")
    src.add_argument("--fresh-init", metavar="SPEC", nargs="?",
                     const="64",
                     help="serve FRESH-INIT weights (no checkpoint): "
                          "SIZE[:BACKBONE[:INJECT]], default "
                          "64:resnet18:head — dev/chaos only (the "
                          "fleet's replica_kill_under_load scenario "
                          "boots its replicas this way; the masks are "
                          "noise, the serving machinery is real)")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8801)
    parser.add_argument("--max-batch", type=int, default=8,
                        help="top micro-batch bucket (power of two); "
                             "buckets are 1/2/4/.../max-batch")
    parser.add_argument("--queue-depth", type=int, default=64,
                        help="bounded request queue; a full queue sheds "
                             "(HTTP 429) instead of growing latency")
    parser.add_argument("--max-wait-ms", type=float, default=5.0,
                        help="batcher hold time waiting to fill a bucket")
    parser.add_argument("--deadline-ms", type=float, default=None,
                        help="default per-request deadline (none = wait)")
    parser.add_argument("--warmup", action="store_true",
                        help="ready every bucket before accepting "
                             "traffic (first clicks pay no compile); "
                             "with --aot-cache, loads pre-compiled "
                             "executables instead of compiling")
    parser.add_argument("--aot-cache", default=None, metavar="DIR",
                        help="AOT executable cache built by dptpu-aot: "
                             "--warmup loads instead of compiling "
                             "(near-zero cold start), falling back "
                             "loudly to fresh compiles on any "
                             "mismatch/corruption")
    parser.add_argument("--quantize", choices=("int8", "none"),
                        default=None,
                        help="post-training weight quantization of the "
                             "serve forward (serve/quantize); default: "
                             "the run config's model.quantization")
    parser.add_argument("--session-budget-mb", type=float, default=256.0,
                        help="HBM byte budget for the per-session encoder "
                             "cache (split predictors only); LRU evicts "
                             "past it")
    parser.add_argument("--session-ttl-s", type=float, default=600.0,
                        help="idle seconds before an abandoned session's "
                             "cached encoding is reaped")
    parser.add_argument("--session-lane-depth", type=int, default=4,
                        help="max queued requests ONE session may hold "
                             "(fairness: excess sheds 429/session_lane)")
    parser.add_argument("--trace-dir", default=None,
                        help="where POST /debug/trace and SIGUSR2 write "
                             "bounded XPlane captures (default: "
                             "<run-dir>/serve_trace, or ./serve_trace)")
    parser.add_argument("--session-log", default=None, metavar="DIR",
                        help="opt-in flywheel sink: append accepted "
                             "(crop, clicks, mask) examples as packed "
                             "records under DIR (crash-safe, deduped, "
                             "budgeted) — the log dptpu-flywheel fine-"
                             "tunes from (docs/DESIGN.md 'The click "
                             "flywheel')")
    args = parser.parse_args(argv)

    from ..telemetry import TraceCapture

    predictor = build_predictor(args)
    trace = TraceCapture(args.trace_dir or os.path.join(
        args.run_dir or ".", "serve_trace"))
    service = InferenceService(
        predictor, max_batch=args.max_batch, queue_depth=args.queue_depth,
        max_wait_s=args.max_wait_ms / 1e3,
        default_deadline_s=None if args.deadline_ms is None
        else args.deadline_ms / 1e3,
        session_budget_bytes=int(args.session_budget_mb * 2**20),
        session_ttl_s=args.session_ttl_s,
        session_lane_depth=args.session_lane_depth,
        aot_cache=args.aot_cache,
        session_log=args.session_log,
        trace=trace)
    if args.warmup:
        # service.warmup (not bare warmup_buckets): it also registers the
        # warmed shapes with the retrace tripwire, keeping its budget
        # exact — and threads through the AOT cache when one is
        # configured (per-bucket compile-vs-load millis land on stderr)
        service.warmup()
    service.start()
    httpd = _Server((args.host, args.port),
                    make_handler(service, _HealthCache()))

    def on_signal(signum, frame):
        # shutdown() must come from another thread than serve_forever's
        threading.Thread(target=httpd.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)
    # SIGUSR2 arms the same bounded capture POST /debug/trace does
    uninstall_trace_signal = trace.install_signal()
    from .quantize import quantization_block

    warm = service.last_warmup
    print(json.dumps({"serving": f"http://{args.host}:{args.port}",
                      "buckets": list(service.buckets),
                      "queue_depth": args.queue_depth,
                      "resolution": list(predictor.resolution),
                      "sessions": service.sessions_enabled,
                      "quantization": quantization_block(
                          getattr(predictor, "quant_policy", None)),
                      "cold_start": None if warm is None else {
                          "warmup_seconds": warm["warmup_seconds"],
                          "programs_compiled": warm["programs_compiled"],
                          "programs_loaded": warm["programs_loaded"],
                          "aot_cache": warm["aot_cache"]}}),
          flush=True)
    try:
        httpd.serve_forever()
    finally:
        # ORDER MATTERS: stopping the service resolves every in-flight and
        # queued future (503s for the queued remainder), which is what the
        # blocked handler threads are waiting on; only then can
        # server_close() join them (non-daemon handlers, see _Server) so
        # each client actually receives its reply before the process exits.
        service.stop()
        httpd.server_close()
        uninstall_trace_signal()
        print(json.dumps({"stopped": True,
                          "stats": service.metrics.snapshot()}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
