"""The inference service: bounded queue -> micro-batcher -> bucketed forward.

``predict.Predictor`` completes the click-to-mask story for ONE caller; this
module amortizes its compiled forward over many concurrent callers — the
keep-the-accelerator-busy principle of the data pipeline (prefetch, echo)
applied to the inference side.  The shape:

    client threads --submit()--> bounded queue --drain--> micro-batcher
                                                              |
         futures <--paste-back <-- unpad <-- bucketed jitted forward

Design points, each load-bearing:

* **Bounded queue, shed at the door.**  An unbounded queue converts
  overload into unbounded latency for everyone; a full queue instead
  rejects the NEW request immediately (:class:`QueueFullError`), which is
  both honest backpressure and the cheapest possible rejection (no device
  work spent).
* **Max-wait/max-batch drain.**  The worker dispatches when ``max_batch``
  requests are pending or ``max_wait_s`` has elapsed since the first one —
  batching gain under load, bounded added latency when idle (a lone
  request waits at most ``max_wait_s``).
* **Power-of-two buckets.**  Every drained batch pads up to the next
  bucket (batching.py), so the service compiles at most one program per
  bucket, ever.  Per-lane independence of the forward (eval-mode BN,
  per-sample attention) makes the padded lanes inert: a request's mask is
  bitwise identical to the same crop run through the shared forward at
  that bucket by hand, and to single-request ``Predictor.predict`` on
  backends whose per-lane results are batch-shape-invariant (different
  shapes compile different programs; XLA may fuse them differently at the
  float32-ulp level — the property tests/test_serve.py pins per backend).
* **Deadlines, checked at drain time.**  A request whose deadline passed
  while queued is dropped (:class:`DeadlineExceededError`) instead of
  occupying a lane to compute an answer nobody is waiting for.
* **Retraces fail loudly.**  A :class:`utils.compile_watchdog
  .CompileWatchdog` runs for the service's lifetime; a compile beyond
  one-per-bucket increments ``retrace_failures``, flips the service
  unhealthy, and (default) refuses further traffic — steady-state
  recompiles cost seconds per occurrence and must never hide.

Host-side preprocessing (clicks -> guidance -> crop) runs on the CALLING
thread in :meth:`InferenceService.submit`, so it parallelizes across
clients instead of serializing in the worker; the worker owns only the
device dispatch and the paste-back.
"""

from __future__ import annotations

import dataclasses
import queue
import sys
import threading
import time
from concurrent.futures import Future
from typing import Any

import numpy as np

from ..chaos import sites as chaos_sites
from ..telemetry import events as events_lib
from ..telemetry.trace import TraceCapture
from ..utils.compile_watchdog import CompileWatchdog
from . import batching
from .metrics import ServeMetrics


class QueueFullError(RuntimeError):
    """Load shed: the bounded request queue is full — retry later."""


class SessionLaneFullError(QueueFullError):
    """Load shed: ONE session overfilled its per-session lane.

    A subclass of :class:`QueueFullError` (same HTTP 429, same retry
    advice) so existing shed handling keeps working — but a distinct
    type, because the remedies differ: a full queue means the SERVICE is
    saturated; a full lane means one chatty session is outpacing its
    fair share and only that session should back off.  Without the lane,
    a single client looping warm clicks could occupy every queue slot
    and starve every other session (the continuous-batching fairness
    hole the taxonomy extension closes)."""


class DeadlineExceededError(TimeoutError):
    """The request's deadline passed before its batch was dispatched."""


class ServiceUnhealthyError(RuntimeError):
    """The service refused the request (stopped, or tripped unhealthy)."""


class _NonFiniteOutputError(RuntimeError):
    """A dispatch produced NaN/inf probabilities — the signal the swap
    pool's canary health tracking keys on (a poisoned checkpoint's
    signature failure mode)."""


class _NonFiniteInputError(RuntimeError):
    """BOTH generations produced non-finite output for the same batch —
    the poison came in with the request (e.g. NaN pixels in a float
    image), not from any params.  Counted as a plain failure, never as
    a canary-health signal: a single hostile request must not be able
    to veto a healthy deploy."""


def warmup_buckets(predictor, buckets) -> list[tuple[int, int, int, int]]:
    """Compile every bucket's program on a bare predictor; returns the
    input shapes it built (resolution and channel count come from the
    predictor).  Service users should call :meth:`InferenceService.warmup`
    instead, which also registers these shapes with the retrace tripwire.
    """
    h, w = predictor.resolution
    ch = getattr(predictor, "in_channels", 4)
    shapes = [(b, h, w, ch) for b in buckets]
    for s in shapes:
        predictor.forward_prepared(np.zeros(s, np.float32))
    return shapes


@dataclasses.dataclass
class _Request:
    """One queued click-segmentation request, already host-preprocessed.

    ``kind='full'``: a stateless request or a session's cold click —
    ``concat`` holds the prepared (H, W, C) network input; with
    ``store_session`` the encoded features are cached under
    ``session_id``.  ``kind='decode'``: a warm click — ``guidance``
    holds only the re-synthesized (H, W, 1) guidance channel and
    ``session`` the cached entry whose features (and crop frame) the
    decode rides on.  ``gen_id`` pins the params generation for the
    request's whole life (serve/swap.py)."""
    bbox: tuple[int, int, int, int]       # paste-back crop box
    shape_hw: tuple[int, int]             # full-image size for paste-back
    future: Future                        # resolves to the (H, W) mask
    submitted: float                      # perf_counter at submit
    deadline: float | None                # absolute perf_counter, or None
    kind: str = "full"                    # full | decode
    concat: np.ndarray | None = None      # full: prepared network input
    guidance: np.ndarray | None = None    # decode: (H, W, 1) guidance
    session: object | None = None         # decode: the sessions.Session
    session_id: str | None = None
    store_session: bool = False           # full: cache features after encode
    gen_id: int = 0                       # params generation (swap routing)
    digest: int = 0                       # session: image fingerprint
    points: np.ndarray | None = None      # full-image xy clicks (4, 2) —
                                          # the session-log sink's record


class InferenceService:
    """Multi-client batched inference over one :class:`predict.Predictor`.

    >>> with InferenceService(predictor, max_batch=8) as svc:
    ...     fut = svc.submit(image, points)          # non-blocking
    ...     mask = fut.result(timeout=5.0)           # (H, W) float32
    ...     mask2 = svc.predict(image2, points2)     # blocking convenience

    ``max_batch`` (power of two) tops the bucket ladder; ``queue_depth``
    bounds admission; ``max_wait_s`` bounds how long the batcher holds a
    lone request hoping for company; ``default_deadline_s`` applies to
    requests submitted without an explicit deadline (None = no deadline).
    ``strict_retrace=False`` keeps serving after a watchdog trip (counted
    and exposed, but not fatal).
    """

    #: substring of the predictor's jitted forward in compile logs
    _FORWARD_NAME = "forward"

    def __init__(self, predictor, max_batch: int = 8,
                 queue_depth: int = 64, max_wait_s: float = 0.005,
                 default_deadline_s: float | None = None,
                 strict_retrace: bool = True,
                 metrics: ServeMetrics | None = None,
                 trace: TraceCapture | None = None,
                 session_budget_bytes: int = 256 << 20,
                 session_ttl_s: float = 600.0,
                 session_lane_depth: int = 4,
                 aot_cache=None,
                 session_log=None):
        if queue_depth < 1:
            raise ValueError(f"queue_depth must be >= 1, got {queue_depth}")
        if max_wait_s < 0:
            raise ValueError(f"max_wait_s must be >= 0, got {max_wait_s}")
        if session_lane_depth < 1:
            raise ValueError(f"session_lane_depth must be >= 1, got "
                             f"{session_lane_depth}")
        self.predictor = predictor
        #: AOT executable cache (serve/aot.py): a path or AotCache, or
        #: None — when set, :meth:`warmup` LOADS pre-compiled
        #: executables instead of compiling (near-zero cold start),
        #: with loud per-program fallback to fresh compile on any
        #: miss/corruption
        if isinstance(aot_cache, str):
            from .aot import AotCache

            aot_cache = AotCache(aot_cache)
        self._aot_cache = aot_cache
        #: the last :meth:`warmup`'s summary (bench.py's `cold_start`
        #: record block reads it); None until a warmup ran
        self.last_warmup: dict | None = None
        self.buckets = batching.bucket_sizes(max_batch)
        self.max_batch = max_batch
        self.max_wait_s = max_wait_s
        self.default_deadline_s = default_deadline_s
        self.strict_retrace = strict_retrace
        self.metrics = metrics or ServeMetrics()
        #: session-affine serving (serve/sessions.py) — available when the
        #: predictor has the encode/decode split (guidance_inject='head');
        #: a stem predictor serves statelessly exactly as before
        self.sessions_enabled = bool(
            getattr(predictor, "supports_sessions", False))
        self.session_lane_depth = session_lane_depth
        self._store = None
        if self.sessions_enabled:
            from .sessions import SessionStore

            self._store = SessionStore(budget_bytes=session_budget_bytes,
                                       ttl_s=session_ttl_s)
        #: params-generation pool (serve/swap.py): generation 0 is the
        #: constructor predictor; hot-swaps add canary generations
        from .swap import PredictorPool

        self._pool = PredictorPool(predictor)
        #: opt-in flywheel sink (serve/session_log.py): a log directory
        #: path or a SessionLogSink; None keeps the request path exactly
        #: as before (one attribute check per dispatch)
        if isinstance(session_log, str):
            from .session_log import SessionLogSink

            session_log = SessionLogSink(
                session_log, resolution=predictor.resolution,
                guidance=predictor.guidance, alpha=predictor.alpha,
                relax=predictor.relax, zero_pad=predictor.zero_pad)
        self._sink = session_log
        #: per-session queued-request counts (the fairness lane)
        self._lane_lock = threading.Lock()
        self._lanes: dict[str, int] = {}
        #: zero-filled decode padding lanes, cached per bucket shape
        self._feat_pad: dict = {}
        self._queue: queue.Queue[_Request] = queue.Queue(maxsize=queue_depth)
        # mute_jax_logs=False: this watchdog stays open for the service's
        # LIFETIME — the default propagation pause would silence every jax
        # warning/error process-wide for as long as we serve
        self._watchdog = CompileWatchdog(match=self._FORWARD_NAME,
                                         mute_jax_logs=False)
        #: on-demand bounded device-trace trigger (telemetry.trace),
        #: armed by POST /debug/trace or SIGUSR2, driven by the worker
        self.trace = trace
        self._shapes_dispatched: set[tuple[int, ...]] = set()
        self._warm_shapes: set[tuple[int, ...]] = set()
        self._unhealthy: str | None = None
        self._stop = threading.Event()
        #: "new" (accepting, queued until start) -> "running" -> "stopped"
        self._state = "new"
        self._worker: threading.Thread | None = None

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "InferenceService":
        """Start the batcher worker.  Requests submitted BEFORE start sit
        in the queue and drain as the first batch — which is also how a
        deterministic multi-request batch is composed in tests."""
        if self._state != "new":
            raise RuntimeError(f"cannot start a {self._state} service")
        # chaos: arm an env-named fault plan (DPTPU_CHAOS_PLAN) for this
        # service's lifetime; one getenv when unset
        chaos_sites.maybe_arm_from_env()
        self._state = "running"
        self._worker = threading.Thread(target=self._run, name="serve-batcher",
                                        daemon=True)
        self._worker.start()
        return self

    def stop(self) -> None:
        """Stop the worker and fail any still-queued requests."""
        if self._state == "stopped":
            return
        self._state = "stopped"
        self._stop.set()
        if self._worker is not None:
            self._worker.join()
            self._worker = None
        if self._sink is not None:
            # the worker is down: commit the log's final meta so every
            # example it appended is readable
            self._sink.flush()
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            try:
                if req.future.set_running_or_notify_cancel():
                    req.future.set_exception(
                        ServiceUnhealthyError("service stopped"))
                    self.metrics.count("failed")
            except RuntimeError:
                # a racing submit() already failed its own future (the
                # post-put guard); never let one resolved future abort
                # the drain and strand the rest
                pass

    def __enter__(self) -> "InferenceService":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop()

    # ------------------------------------------------------------ front door

    def submit(self, image: np.ndarray, points: Any,
               deadline_s: float | None = None,
               session_id: str | None = None) -> Future:
        """Enqueue one request; returns a Future resolving to the mask.

        Host-side preprocessing runs here, on the caller's thread.  Raises
        :class:`QueueFullError` immediately when the bounded queue is full
        (shed, don't wait), :class:`SessionLaneFullError` when ONE session
        overfilled its fair-share lane, and
        :class:`ServiceUnhealthyError` when the service is stopped or
        tripped unhealthy.  Bad inputs (malformed points, clicks outside
        the image) raise ``ValueError`` here, before anything is queued.

        ``session_id`` opts into session-affine serving (split predictors
        only): the first click encodes and caches the crop's backbone
        features; later clicks inside the same crop pay only a decode.
        Absent (the default), the request is stateless — the pre-session
        wire unchanged.
        """
        if self._state == "stopped":
            raise ServiceUnhealthyError("service stopped")
        if self._unhealthy and self.strict_retrace:
            raise ServiceUnhealthyError(self._unhealthy)
        if session_id is not None and not self.sessions_enabled:
            raise ValueError(
                "session_id needs a split predictor (model built with "
                "guidance_inject='head'); this service's predictor folds "
                "the guidance into the backbone — submit statelessly")
        # chaos seam, on the CALLER's thread: latency is a slow host
        # preprocess (builds queue pressure), an error is a front-door
        # dependency failing — both before anything is queued
        chaos_sites.fire("serve/enqueue")
        if self._queue.full():
            # fast-path shed BEFORE the (expensive) host preprocessing:
            # under overload a rejection must not cost nearly as much host
            # CPU as serving would.  Best-effort (racy by nature); the
            # put_nowait below is the authoritative check.
            self.metrics.count("shed_queue_full")
            raise QueueFullError(
                f"request queue full ({self._queue.maxsize} deep) — "
                "overloaded; retry with backoff")
        if session_id is not None:
            self._check_session_lane(session_id)
        req = self._build_request(image, points, deadline_s, session_id)
        # reserve lane + generation-inflight accounting BEFORE the
        # enqueue: booked after, a racing housekeeping gc could retire a
        # generation whose request is already queued, and N concurrent
        # submitters of one session could all clear the lane check
        self._track_request(req)
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            self._untrack_request(req)
            self.metrics.count("shed_queue_full")
            raise QueueFullError(
                f"request queue full ({self._queue.maxsize} deep) — "
                "overloaded; retry with backoff") from None
        self.metrics.count("requests")
        if self._state == "stopped" and not req.future.done():
            # raced a concurrent stop() past its queue drain: fail the
            # future now rather than strand the caller until their timeout
            try:
                if req.future.set_running_or_notify_cancel():
                    req.future.set_exception(
                        ServiceUnhealthyError("service stopped"))
            except RuntimeError:
                pass  # stop()'s drain got it first — already resolved
        return req.future

    def _build_request(self, image, points, deadline_s,
                       session_id) -> _Request:
        """Route + host-preprocess one request on the caller's thread."""
        now = time.perf_counter()
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        deadline = None if deadline_s is None else now + deadline_s
        shape_hw = tuple(np.asarray(image).shape[:2])
        if session_id is not None:
            from .sessions import image_digest

            # the warm path bypasses prepare_input, so it must apply the
            # SAME input validation here: malformed/out-of-image points
            # are a 400-class ValueError on every path, never an
            # IndexError from covers() (a 500) nor a silently-served
            # out-of-image click that the stateless path would reject
            pts = np.asarray(points, np.float64)
            if pts.shape != (4, 2):
                raise ValueError(
                    f"expected 4 xy extreme points, got {pts.shape}")
            h_img, w_img = shape_hw
            if (pts[:, 0].max() >= w_img or pts[:, 1].max() >= h_img
                    or pts.min() < 0):
                raise ValueError(f"points {pts.tolist()} outside image "
                                 f"{w_img}x{h_img}")
            digest = image_digest(image)
            sess = self._store.get(session_id)
            pred = (None if sess is None
                    else self._pool.predictor_for(sess.generation))
            if (sess is not None and pred is not None
                    and sess.covers(points, shape_hw, digest=digest)):
                # warm click: only the guidance channel is re-synthesized,
                # in the SESSION's crop coordinates; the dispatch is a
                # decode against the cached features, on the generation
                # that encoded them (swap affinity).  A sess whose
                # generation was retired under it (rollback-eviction
                # race) degrades to the cold path below.
                self._store.hit()
                guidance = pred.prepare_guidance(points, sess.bbox)
                # digest rides the completed request: the sink dedups off
                # the submit thread's hash, re-hashing never
                return _Request(kind="decode", guidance=guidance,
                                session=sess, session_id=session_id,
                                bbox=sess.bbox, shape_hw=sess.shape_hw,
                                gen_id=sess.generation, digest=digest,
                                points=pts, future=Future(),
                                submitted=now, deadline=deadline)
            # cold click (new session, TTL-expired, clicks outside the
            # cached crop, or a different image under a reused id):
            # full encode+decode, then cache the features
            self._store.miss()
            gen_id, pred = self._pool.route(session_id)
            concat, bbox = pred.prepare(image, points)
            return _Request(kind="full", concat=concat, bbox=bbox,
                            shape_hw=shape_hw, session_id=session_id,
                            store_session=True, gen_id=gen_id,
                            digest=digest, points=pts,
                            future=Future(), submitted=now,
                            deadline=deadline)
        gen_id, pred = self._pool.route(None)
        concat, bbox = pred.prepare(image, points)
        return _Request(kind="full", concat=concat, bbox=bbox,
                        shape_hw=shape_hw, gen_id=gen_id,
                        points=np.asarray(points, np.float64),
                        future=Future(), submitted=now, deadline=deadline)

    def _check_session_lane(self, session_id: str) -> None:
        """Per-session fairness fast path: cap how many of the bounded
        queue's slots one session may hold, checked BEFORE the
        (expensive) host preprocessing — same move as the queue-full
        fast path.  Best-effort under concurrency; the atomic
        reservation in :meth:`_track_request` is authoritative."""
        with self._lane_lock:
            if self._lanes.get(session_id, 0) >= self.session_lane_depth:
                self.metrics.count("shed_session_lane")
                raise SessionLaneFullError(
                    f"session {session_id!r} already holds "
                    f"{self.session_lane_depth} queued request(s) — one "
                    "session cannot starve the others; retry with backoff")

    def _track_request(self, req: _Request) -> None:
        """Atomically reserve the lane slot + generation in-flight count,
        released by the future's done callback — which fires on EVERY
        resolution path (result, error, shed at drain, cancel, stop
        drain), so the counts can never leak.  The lane check here is
        the authoritative one: check-and-increment under one lock, so
        concurrent submitters of one session cannot overshoot the
        depth."""
        sid, gen = req.session_id, req.gen_id
        if sid is not None:
            with self._lane_lock:
                n = self._lanes.get(sid, 0)
                if n >= self.session_lane_depth:
                    self.metrics.count("shed_session_lane")
                    raise SessionLaneFullError(
                        f"session {sid!r} already holds "
                        f"{self.session_lane_depth} queued request(s) — "
                        "one session cannot starve the others; retry "
                        "with backoff")
                self._lanes[sid] = n + 1
        self._pool.track_inflight(gen, +1)
        req.future.add_done_callback(lambda _f: self._untrack_request(req))

    def _untrack_request(self, req: _Request) -> None:
        sid = req.session_id
        if sid is not None:
            with self._lane_lock:
                n = self._lanes.get(sid, 1) - 1
                if n <= 0:
                    self._lanes.pop(sid, None)
                else:
                    self._lanes[sid] = n
        self._pool.track_inflight(req.gen_id, -1)

    def predict(self, image: np.ndarray, points: Any,
                deadline_s: float | None = None,
                timeout: float | None = None,
                session_id: str | None = None) -> np.ndarray:
        """Blocking convenience: :meth:`submit` + ``Future.result``."""
        return self.submit(image, points, deadline_s,
                           session_id=session_id).result(timeout)

    def warmup(self) -> dict:
        """Ready every bucket's program before taking traffic: a cold
        service otherwise charges its first unlucky clients the XLA
        compile — exactly the latency cliff the bucket ladder prevents.
        A split predictor warms TWO programs per bucket (encode at the
        crop shape, decode at the feature shape).

        With an ``aot_cache`` configured, each program LOADS its
        pre-compiled executable (``dptpu-aot``) instead of compiling —
        a warm-cache boot performs ZERO XLA compiles (watchdog-
        verified in tests/test_aot.py).  A missing/mismatched entry
        compiles fresh with a loud stderr line naming why; a corrupt
        entry (checksum) is REFUSED the same way — degraded cold
        start, never a silently-wrong executable.  Per-program
        compile-vs-load millis are logged either way and returned (and
        kept as :attr:`last_warmup` — the bench `cold_start` block).

        The warmed shapes are registered with the retrace tripwire: these
        compiles happen on the CALLING thread (invisible to the worker's
        thread-local watchdog), so without registration the budget would
        silently allow that many real steady-state retraces before
        tripping."""
        from .aot import AotCacheError, AotCacheMiss, ladder_programs

        t0 = time.perf_counter()
        cache = self._aot_cache
        fingerprint = None
        if cache is not None and getattr(self.predictor, "mesh",
                                         None) is not None:
            print("serve/aot: cache disabled for this boot — mesh "
                  "predictors compile process-local GSPMD programs",
                  file=sys.stderr)
            cache = None
        if cache is not None:
            from .aot import cache_fingerprint

            try:
                fingerprint = cache_fingerprint(self.predictor)
            except Exception as e:  # fingerprinting never kills a boot
                print(f"serve/aot: cache disabled for this boot — "
                      f"fingerprinting failed ({type(e).__name__}: {e})",
                      file=sys.stderr)
                cache = None
        programs = []
        pred = self.predictor
        h, w = pred.resolution
        ch = getattr(pred, "in_channels", 4)
        for name, _fn, _args, key in ladder_programs(pred, self.buckets):
            kind = key[0]
            b = key[1] if kind != "forward" else key[1][0]
            if kind == "forward":
                def compile_fn(b=b):
                    pred.forward_prepared(np.zeros((b, h, w, ch),
                                                   np.float32))
                warm_key = (*self._compiled_shape((b, h, w, ch)),
                            self._pred_key(pred))
            elif kind == "encode":
                def compile_fn(b=b):
                    pred.encode_jitted(np.zeros((b, h, w, ch - 1),
                                                np.float32))
                warm_key = ("enc", b, self._pred_key(pred))
            else:
                def compile_fn(b=b):
                    feats = pred.encode_jitted(
                        np.zeros((b, h, w, ch - 1), np.float32))
                    pred.decode_jitted(feats, np.zeros((b, h, w, 1),
                                                       np.float32))
                warm_key = ("dec", b, self._pred_key(pred))
            programs.append((name, key, warm_key, compile_fn))

        log: list[dict] = []
        for name, key, warm_key, compile_fn in programs:
            p0 = time.perf_counter()
            outcome, fallback = "compile", None
            if cache is not None:
                try:
                    exe = cache.load(name, fingerprint)
                    pred.install_aot(key, exe)
                    outcome = "load"
                except AotCacheMiss as e:
                    fallback = "miss"
                    print(f"serve/aot: miss for {name!r}: {e} — "
                          "compiling fresh", file=sys.stderr)
                except AotCacheError as e:
                    fallback = "error"
                    print(f"serve/aot: REFUSING cache entry {name!r}: "
                          f"{e} — falling back to fresh compile",
                          file=sys.stderr)
                except Exception as e:  # noqa: BLE001 — the backstop:
                    # a corrupt cache is a degraded cold start, NEVER a
                    # dead boot; anything the typed paths missed still
                    # falls back to a fresh compile, loudly
                    fallback = "error"
                    print(f"serve/aot: unexpected failure loading "
                          f"{name!r} ({type(e).__name__}: {e}) — "
                          "falling back to fresh compile",
                          file=sys.stderr)
            if outcome == "compile":
                compile_fn()
            self._warm_shapes.add(warm_key)
            ms = (time.perf_counter() - p0) * 1e3
            log.append({"program": name, "outcome": outcome,
                        "fallback": fallback, "ms": round(ms, 3)})
            # the operator's per-bucket compile-vs-load ledger — the
            # cold-start tax made visible whether or not a cache is on
            print(f"serve/warmup: {name}: {outcome} {ms:.1f} ms"
                  + (f" (cache {fallback})" if fallback else ""),
                  file=sys.stderr)
        loaded = sum(1 for e in log if e["outcome"] == "load")
        compiled = len(log) - loaded
        if self._aot_cache is None:
            aot = "off"
        elif compiled == 0 and loaded:
            aot = "hit"
        elif loaded:
            aot = "partial"
        else:
            aot = "miss"
        self.last_warmup = {
            "warmup_seconds": round(time.perf_counter() - t0, 4),
            "programs_compiled": compiled,
            "programs_loaded": loaded,
            "aot_cache": aot,
            "programs": log,
        }
        return self.last_warmup

    def _warm_split_predictor(self, pred) -> None:
        """Compile a split predictor's encode+decode ladder on the
        CALLING thread (also the hot-swap admission path: a swapped-in
        generation must pay its XLA compiles before it sees traffic, or
        the first canary clicks eat seconds of compile AND the worker's
        watchdog books compiles it has no shape budget for)."""
        h, w = pred.resolution
        ch = getattr(pred, "in_channels", 4)
        for b in self.buckets:
            feats = pred.encode_jitted(np.zeros((b, h, w, ch - 1),
                                                np.float32))
            pred.decode_jitted(feats, np.zeros((b, h, w, 1), np.float32))
            self._warm_shapes.add(("enc", b, self._pred_key(pred)))
            self._warm_shapes.add(("dec", b, self._pred_key(pred)))

    # ------------------------------------------------------------- hot-swap

    #: distinguishes "leave the pool's promote_after alone" from the
    #: meaningful None (= manual promotion only)
    _UNSET = object()

    def swap(self, predictor, label: str = "",
             canary_fraction: float | None = None,
             warmup: bool = True,
             min_observations: int | None = None,
             max_error_rate: float | None = None,
             promote_after=_UNSET) -> int:
        """Admit a new checkpoint's predictor as the canary generation —
        zero downtime: live sessions keep decoding on THEIR generation's
        params; only a ``canary_fraction`` of new sessions/stateless
        requests route to the new params until :meth:`promote` /
        :meth:`rollback` (or the pool's auto-decision from observed
        error rates; an injected-NaN checkpoint rolls back on its first
        poisoned output).  The compile cost lands HERE, on the calling
        thread, before any traffic routes to the new generation."""
        if self.sessions_enabled and not getattr(
                predictor, "supports_sessions", False):
            raise ValueError(
                "swap: this service serves sessions; the new predictor "
                "must keep the encode/decode split "
                "(guidance_inject='head')")
        if tuple(predictor.resolution) != tuple(self.predictor.resolution):
            raise ValueError(
                f"swap: resolution {predictor.resolution} != the "
                f"service's {self.predictor.resolution} — the bucket "
                "ladder's compiled programs are resolution-keyed")
        if self._pool.canary_generation is not None:
            # fail fast BEFORE the (seconds of) warmup compile and before
            # touching any decision thresholds: a refused swap must leave
            # the in-flight canary's configuration untouched.  begin_swap
            # re-checks under its lock (authoritative on a race).
            from .swap import SwapInProgressError

            raise SwapInProgressError(
                f"generation {self._pool.canary_generation} is still "
                "canarying — promote() or rollback() before swapping "
                "again")
        if warmup:
            if getattr(predictor, "supports_sessions", False):
                self._warm_split_predictor(predictor)
            else:
                for shape in warmup_buckets(predictor, self.buckets):
                    self._warm_shapes.add((*self._compiled_shape(shape),
                                           self._pred_key(predictor)))
        gen = self._pool.begin_swap(predictor, label=label,
                                    canary_fraction=canary_fraction)
        # thresholds only after a successful admission — they configure
        # THIS canary's decision rules, not whatever was already running
        if min_observations is not None:
            self._pool.min_observations = int(min_observations)
        if max_error_rate is not None:
            self._pool.max_error_rate = float(max_error_rate)
        if promote_after is not InferenceService._UNSET:
            self._pool.promote_after = promote_after
        return gen

    def promote(self) -> dict:
        """Promote the canary to active; the old active generation drains
        (serves its remaining sessions) and is retired when empty."""
        return self._pool.promote()

    def rollback(self) -> dict:
        """Roll the canary back; its sessions are evicted (their features
        came from the rolled-back params) and re-encode cold on the
        active generation at their next click."""
        gen = self._pool.canary_generation
        out = self._pool.rollback()
        if gen is not None and self._store is not None:
            self._store.evict_generation(gen)
        return out

    # ------------------------------------------------------------ ops surface

    def health(self) -> dict:
        """Liveness + the counters a probe needs to decide 'still good'."""
        out = {
            "ok": self._state == "running" and self._unhealthy is None,
            "running": self._state == "running",
            "state": self._state,
            "unhealthy_reason": self._unhealthy,
            "queue_depth": self._queue.qsize(),
            "queue_capacity": self._queue.maxsize,
            "buckets": list(self.buckets),
            "stats": self.metrics.snapshot(),
            "sessions": (self._store.snapshot()
                         if self._store is not None else None),
            "swap": self._pool.snapshot(),
            # flywheel intake (serve/session_log.py); None when the sink
            # is off — the always-present-key convention
            "session_log": (self._sink.snapshot()
                            if self._sink is not None else None),
            # flight recorder (telemetry/events.py): emitted/dropped/path
            # of this process's event log; all-None when none configured
            "events": events_lib.events_block(),
        }
        return out

    def audit_programs(self, buckets=None) -> dict:
        """``{serve_forward_b<N>: (fn, args)}`` for the EXACT jitted
        forward at each bucket's compiled shape (mesh padding included,
        :meth:`_compiled_shape`) — the hook jaxaudit (analysis.ir)
        traces and the checked-in serve contracts pin.  Args are
        ShapeDtypeStructs; tracing never dispatches.

        A split predictor has no single jitted forward; its programs are
        the two stages, named ``serve_encode_b<N>``/``serve_decode_b<N>``
        per bucket (the canonical single-click pins are the
        ``encode_step``/``decode_step`` contracts, analysis/contracts)."""
        import jax
        import jax.numpy as jnp

        h, w = self.predictor.resolution
        ch = getattr(self.predictor, "in_channels", 4)
        buckets = buckets if buckets is not None else self.buckets
        if self.sessions_enabled:
            feats1 = self.predictor.feature_struct(1)
            out: dict = {}
            for b in buckets:
                fstruct = jax.ShapeDtypeStruct((b, *feats1.shape[1:]),
                                               feats1.dtype)
                out[f"serve_encode_b{b}"] = (
                    self.predictor.encode_jitted,
                    (jax.ShapeDtypeStruct((b, h, w, ch - 1),
                                          jnp.float32),))
                out[f"serve_decode_b{b}"] = (
                    self.predictor.decode_jitted,
                    (fstruct,
                     jax.ShapeDtypeStruct((b, h, w, 1), jnp.float32)))
            return out
        fn = self.predictor.forward_jitted
        return {
            f"serve_forward_b{b}": (fn, (jax.ShapeDtypeStruct(
                self._compiled_shape((b, h, w, ch)), jnp.float32),))
            for b in buckets
        }

    def audit(self, buckets=None, **kwargs) -> dict:
        """jaxaudit reports for the bucket forwards (see analysis.ir)."""
        from ..analysis import ir as ir_lib

        return ir_lib.audit_many(self.audit_programs(buckets), **kwargs)

    @property
    def compile_counts(self) -> dict:
        """Forward-compile counts seen by the lifetime watchdog."""
        return dict(self._watchdog.counts)

    @property
    def buckets_compiled(self) -> set[int]:
        """Bucket sizes dispatched (== compiled, absent retraces).
        Split-predictor entries are kind-tagged ('enc'/'dec', bucket);
        whole-forward entries are full compiled shapes — both reduce to
        the bucket size here."""
        return {s[1] if isinstance(s[0], str) else s[0]
                for s in self._shapes_dispatched}

    # ------------------------------------------------------------ worker

    def _run(self) -> None:
        # The watchdog must live on THIS thread: jax.log_compiles() is a
        # thread-local config context, and every forward dispatch (hence
        # every compile) happens here.  A watchdog entered on the caller's
        # thread would count nothing and silently disarm the retrace check.
        with self._watchdog:
            last_sweep = time.perf_counter()
            while not self._stop.is_set():
                batch = self._gather()
                if self.trace is not None:
                    # drive the on-demand capture from the worker (the
                    # only thread dispatching device work): 1 step per
                    # batch, 0 on idle polls so the wall-clock backstop
                    # still closes a capture when traffic stops
                    self.trace.tick(1 if batch else 0)
                if batch:
                    self._process(batch)
                now = time.perf_counter()
                if now - last_sweep > 1.0:
                    # periodic housekeeping between drains: TTL-reap
                    # abandoned sessions, retire drained generations.
                    # The gc runs store-less too — a stateless service
                    # that hot-swaps still needs its old generations'
                    # params freed once they drain.
                    last_sweep = now
                    if self._sink is not None:
                        # commit the session log's meta at the same 1 Hz
                        # cadence: appends stay buffered between ticks,
                        # so the hot path never pays the atomic-replace
                        self._sink.flush()
                    if self._store is not None:
                        self._store.sweep()
                    freed = self._pool.gc(
                        self._store.counts_by_generation()
                        if self._store is not None else {})
                    if freed and not self._pool.is_resident(
                            self.predictor):
                        # the base predictor's generation just retired:
                        # re-point at the active generation so the old
                        # params (and their compiled ladder) actually
                        # free — keeping the constructor's reference
                        # would pin one dead param set per service
                        # forever.  Settings are interchangeable:
                        # load_swap_predictor inherits them from the
                        # predictor in service.
                        self.predictor = self._pool.active_predictor
            if self.trace is not None:
                self.trace.close()

    def _gather(self) -> list[_Request]:
        """Drain on the max-wait/max-batch policy: dispatch when
        ``max_batch`` requests are pending OR ``max_wait_s`` has elapsed
        since the first one was picked up.  The window bounds WAITING for
        company only — requests already sitting in the queue are always
        drained (even at ``max_wait_s=0``), or a pre-loaded backlog would
        trickle out one lane at a time."""
        try:
            first = self._queue.get(timeout=0.05)
        except queue.Empty:
            return []
        batch = [first]
        wait_until = time.perf_counter() + self.max_wait_s
        while len(batch) < self.max_batch:
            remaining = wait_until - time.perf_counter()
            try:
                if remaining > 0:
                    batch.append(self._queue.get(timeout=remaining))
                else:
                    batch.append(self._queue.get_nowait())
            except queue.Empty:
                if remaining <= 0:
                    break
        return batch

    def _process(self, batch: list[_Request]) -> None:
        # chaos seam, on the WORKER thread before the deadline check:
        # injected latency stalls the whole drain exactly like a slow
        # device — queued deadlines expire (504 shed) and the bounded
        # queue backs up (429 shed), which is the degradation the
        # serve-latency scenario asserts instead of a crash.  A raised
        # fault fails THIS batch and the worker serves on (the same
        # fail-the-batch contract the forward's except clause keeps).
        try:
            chaos_sites.fire("serve/drain", batch_size=len(batch))
        except Exception as e:
            failed = 0
            for req in batch:
                if req.future.set_running_or_notify_cancel():
                    req.future.set_exception(e)
                    failed += 1
            self.metrics.count("failed", failed)
            return
        now = time.perf_counter()
        live: list[_Request] = []
        for req in batch:
            if not req.future.set_running_or_notify_cancel():
                continue                       # client gave up; skip the lane
            if req.deadline is not None and now > req.deadline:
                self.metrics.count("shed_deadline")
                req.future.set_exception(DeadlineExceededError(
                    "deadline passed while queued — the service is "
                    "saturated; shed instead of serving a stale answer"))
                continue
            live.append(req)
        if not live:
            return
        # continuous batching across sessions: one drain may hold decode
        # requests from MANY sessions plus full forwards, and (during a
        # swap window) several params generations.  A dispatch group is
        # (kind, generation): decodes batch together whatever session
        # they came from; generations can never share a program (their
        # params differ).  Order is drain order — the group holding the
        # oldest request dispatches first.
        groups: dict[tuple[str, int], list[_Request]] = {}
        for req in live:
            groups.setdefault((req.kind, req.gen_id), []).append(req)
        for (kind, gen_id), reqs in groups.items():
            self._dispatch_group(kind, gen_id, reqs)

    def _dispatch_group(self, kind: str, gen_id: int,
                        live: list[_Request]) -> None:
        try:
            bucket = batching.bucket_for(len(live), self.buckets)
            if kind == "decode":
                probs, gen_used = self._decode_batch(gen_id, live, bucket)
            else:
                probs, gen_used = self._full_batch(gen_id, live, bucket)
            self._check_retrace()
            for i, req in enumerate(live):
                req.future.set_result(self.predictor.paste_back(
                    probs[i], req.bbox, req.shape_hw))
            if self._sink is not None:
                # flywheel capture, AFTER the futures resolved: the
                # example is the (crop, clicks, mask) the client just
                # accepted, and a sink hiccup must never fail a request
                for i, req in enumerate(live):
                    self._sink.offer(req, probs[i])
            self.metrics.observe_batch(bucket, len(live))
            self.metrics.count("completed", len(live))
            done = time.perf_counter()
            for req in live:
                self.metrics.observe_latency(done - req.submitted)
                self._observe_generation(gen_used, ok=True)
        except Exception as e:                       # fail the batch, serve on
            failed = 0
            for req in live:
                if not req.future.done():            # not the already-resolved
                    req.future.set_exception(e)
                    failed += 1
                self._observe_generation(
                    gen_id, ok=False,
                    nonfinite=isinstance(e, _NonFiniteOutputError))
            self.metrics.count("failed", failed)

    def _full_batch(self, gen_id: int, live: list[_Request],
                    bucket: int) -> tuple[np.ndarray, int]:
        """Dispatch a full (encode+decode or whole-forward) group; caches
        features for cold session clicks.  Returns (probs, generation
        that actually served) — a NaN-poisoned canary fails over to the
        active generation so the clients still get masks (and the canary
        observation triggers the rollback)."""
        pred = self._pool.predictor_for(gen_id)
        padded = batching.pad_to_bucket(
            np.stack([r.concat for r in live]), bucket)
        probs, feats = self._run_full(pred, padded, bucket)
        if not np.isfinite(probs[:len(live)]).all():
            active = self._pool.active_generation
            if gen_id == active:
                raise _NonFiniteOutputError(
                    f"non-finite probabilities from active generation "
                    f"{gen_id}")
            # canary output poisoned — but only blame the CANARY PARAMS
            # if the active generation can serve the same batch finitely
            # (a request carrying NaN pixels poisons every generation
            # equally and must not roll a healthy deploy back).  The
            # cold click still has its full input, so the failover costs
            # one extra forward, not an error surfaced to any client.
            probs2, feats2 = self._run_full(
                self._pool.predictor_for(active), padded, bucket)
            if not np.isfinite(probs2[:len(live)]).all():
                raise _NonFiniteInputError(
                    "non-finite probabilities from BOTH generations — "
                    "the request input is poisoned, not the params")
            self._observe_generation(gen_id, ok=False, nonfinite=True)
            gen_id, probs, feats = active, probs2, feats2
        for i, req in enumerate(live):
            if req.store_session and feats is not None:
                self._store.put(req.session_id, feats[i:i + 1],
                                req.bbox, req.shape_hw, gen_id,
                                digest=req.digest)
        return batching.unpad(probs, len(live)), gen_id

    def _run_full(self, pred, padded: np.ndarray,
                  bucket: int) -> tuple[np.ndarray, object]:
        """One full forward at a bucket; split predictors run their two
        stages explicitly so the encoded features are in hand for the
        session cache (the same two programs the stateless composition
        dispatches — warm/cold parity stays bitwise).

        Retrace-budget keys carry the PREDICTOR identity
        (:meth:`_pred_key`): each generation owns its own jit cache, so
        an unwarmed swapped-in generation's first dispatches are new
        compiles the budget must grow for — generation-agnostic keys
        would false-trip the tripwire on the first swap(warmup=False)."""
        if getattr(pred, "supports_sessions", False):
            feats = pred.encode_jitted(padded[..., :-1])
            probs = np.asarray(pred.decode_jitted(
                feats, padded[..., -1:]))[..., 0]
            # register AFTER a successful forward: a dispatch that dies
            # mid-compile must not leave a phantom shape that either
            # false-trips the tripwire on retry or pads its budget
            self._shapes_dispatched.add(("enc", bucket, self._pred_key(pred)))
            self._shapes_dispatched.add(("dec", bucket, self._pred_key(pred)))
            return probs, feats
        probs = pred.forward_prepared(padded)
        self._shapes_dispatched.add(
            (*self._compiled_shape(padded.shape), self._pred_key(pred)))
        return probs, None

    def _pred_key(self, pred) -> int:
        """Stable per-predictor tag for warm/dispatched program keys.
        ``id()`` is stable for the predictor's lifetime (the pool holds
        it while any key matters); after retirement an id could in
        principle be reused by a later predictor, whose ladder would
        then inherit that slack — bounded at one ladder of budget,
        accepted for the simplicity."""
        return id(pred)

    def _decode_batch(self, gen_id: int, live: list[_Request],
                      bucket: int) -> tuple[np.ndarray, int]:
        """Warm clicks: decode cached features from MANY sessions in one
        bucketed dispatch.  Features stay on device end to end — the
        stack is a device-side concatenate, never a host round trip."""
        import jax.numpy as jnp

        pred = self._pool.predictor_for(gen_id)
        guidance = batching.pad_to_bucket(
            np.stack([r.guidance for r in live]), bucket)
        feat_list = [r.session.features for r in live]
        n_pad = bucket - len(feat_list)
        if n_pad:
            shape = feat_list[0].shape
            key = (n_pad, *shape[1:])
            pad = self._feat_pad.get(key)
            if pad is None:
                pad = self._feat_pad[key] = jnp.zeros(
                    (n_pad, *shape[1:]), feat_list[0].dtype)
            feat_list = feat_list + [pad]
        feats = (jnp.concatenate(feat_list, axis=0)
                 if len(feat_list) > 1 else feat_list[0])
        probs = np.asarray(pred.decode_jitted(feats, guidance))[..., 0]
        self._shapes_dispatched.add(("dec", bucket, self._pred_key(pred)))
        if not np.isfinite(probs[:len(live)]).all():
            # a decode has no image to re-encode from, so there is no
            # failover — but a poisoned canary is caught on its COLD
            # click (which can fail over), so a non-finite decode means
            # the generation degraded after admission: fail the group
            # and let the observation roll the canary back
            raise _NonFiniteOutputError(
                f"non-finite probabilities decoding generation {gen_id}")
        for req in live:
            self._store.touch_click(req.session)
        return batching.unpad(probs, len(live)), gen_id

    def _observe_generation(self, gen_id: int, ok: bool,
                            nonfinite: bool = False) -> None:
        """Report one outcome to the swap pool; apply its decision (a
        rollback evicts the rolled-back generation's sessions — their
        features must never outlive their params)."""
        action = self._pool.observe(gen_id, ok=ok, nonfinite=nonfinite)
        if action == "rolled_back" and self._store is not None:
            self._store.evict_generation(gen_id)

    def _compiled_shape(self, shape: tuple[int, ...]) -> tuple[int, ...]:
        """The shape the forward actually COMPILES for a bucket dispatch.

        A mesh predictor additionally pads the batch up to the data-axis
        extent inside ``forward_prepared`` (mesh.pad_to_multiple), which
        can collapse several buckets onto one program — keying the retrace
        check on the pre-mesh shape would over-count expected programs and
        desensitize the tripwire by exactly that margin."""
        mesh = getattr(self.predictor, "mesh", None)
        if mesh is None:
            return shape
        from ..parallel.mesh import DATA_AXIS
        m = mesh.shape[DATA_AXIS]
        return (-(-shape[0] // m) * m, *shape[1:])

    def _check_retrace(self) -> None:
        """One compile per bucket, ever: more forward compiles than
        distinct dispatched shapes means a steady-state retrace (shape
        drift, donation mismatch, tracer-dependent Python) — the failure
        jaxlint hunts statically, caught here at runtime.  Shapes warmed
        via :meth:`warmup` are excluded from the budget (their compiles
        happened off-worker, so dispatching them must cost ZERO watched
        compiles — the tripwire fires on the very first retrace)."""
        compiles = sum(self._watchdog.counts.values())
        budget = len(self._shapes_dispatched - self._warm_shapes)
        if compiles > budget:
            self.metrics.count("retrace_failures")
            self._unhealthy = (
                f"steady-state retrace: {compiles} forward compiles for "
                f"{budget} cold batch shapes "
                f"(counts: {dict(self._watchdog.counts)}) — run jaxlint")
