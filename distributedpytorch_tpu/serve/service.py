"""The inference service: bounded queue -> micro-batcher -> bucketed forward.

``predict.Predictor`` completes the click-to-mask story for ONE caller; this
module amortizes its compiled forward over many concurrent callers — the
keep-the-accelerator-busy principle of the data pipeline (prefetch, echo)
applied to the inference side.  The shape:

    client threads --submit()--> bounded queue --drain--> micro-batcher
                                                              |
         futures <--paste-back <-- unpad <-- bucketed jitted forward

Design points, each load-bearing:

* **Bounded queue, shed at the door.**  An unbounded queue converts
  overload into unbounded latency for everyone; a full queue instead
  rejects the NEW request immediately (:class:`QueueFullError`), which is
  both honest backpressure and the cheapest possible rejection (no device
  work spent).
* **Max-wait/max-batch drain.**  The worker dispatches when ``max_batch``
  requests are pending or ``max_wait_s`` has elapsed since the first one —
  batching gain under load, bounded added latency when idle (a lone
  request waits at most ``max_wait_s``).
* **Power-of-two buckets.**  Every drained batch pads up to the next
  bucket (batching.py), so the service compiles at most one program per
  bucket, ever.  Per-lane independence of the forward (eval-mode BN,
  per-sample attention) makes the padded lanes inert: a request's mask is
  bitwise identical to the same crop run through the shared forward at
  that bucket by hand, and to single-request ``Predictor.predict`` on
  backends whose per-lane results are batch-shape-invariant (different
  shapes compile different programs; XLA may fuse them differently at the
  float32-ulp level — the property tests/test_serve.py pins per backend).
* **Deadlines, checked at drain time.**  A request whose deadline passed
  while queued is dropped (:class:`DeadlineExceededError`) instead of
  occupying a lane to compute an answer nobody is waiting for.
* **Retraces fail loudly.**  A :class:`utils.compile_watchdog
  .CompileWatchdog` runs for the service's lifetime; a compile beyond
  one-per-bucket increments ``retrace_failures``, flips the service
  unhealthy, and (default) refuses further traffic — steady-state
  recompiles cost seconds per occurrence and must never hide.

Host-side preprocessing (clicks -> guidance -> crop) runs on the CALLING
thread in :meth:`InferenceService.submit`, so it parallelizes across
clients instead of serializing in the worker; the worker owns only the
device dispatch and the paste-back.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from concurrent.futures import Future
from typing import Any

import numpy as np

from ..chaos import sites as chaos_sites
from ..telemetry.trace import TraceCapture
from ..utils.compile_watchdog import CompileWatchdog
from . import batching
from .metrics import ServeMetrics


class QueueFullError(RuntimeError):
    """Load shed: the bounded request queue is full — retry later."""


class DeadlineExceededError(TimeoutError):
    """The request's deadline passed before its batch was dispatched."""


class ServiceUnhealthyError(RuntimeError):
    """The service refused the request (stopped, or tripped unhealthy)."""


def warmup_buckets(predictor, buckets) -> list[tuple[int, int, int, int]]:
    """Compile every bucket's program on a bare predictor; returns the
    input shapes it built (resolution and channel count come from the
    predictor).  Service users should call :meth:`InferenceService.warmup`
    instead, which also registers these shapes with the retrace tripwire.
    """
    h, w = predictor.resolution
    ch = getattr(predictor, "in_channels", 4)
    shapes = [(b, h, w, ch) for b in buckets]
    for s in shapes:
        predictor.forward_prepared(np.zeros(s, np.float32))
    return shapes


@dataclasses.dataclass
class _Request:
    """One queued click-segmentation request, already host-preprocessed."""
    concat: np.ndarray                    # (H, W, C) prepared network input
    bbox: tuple[int, int, int, int]       # paste-back crop box
    shape_hw: tuple[int, int]             # full-image size for paste-back
    future: Future                        # resolves to the (H, W) mask
    submitted: float                      # perf_counter at submit
    deadline: float | None                # absolute perf_counter, or None


class InferenceService:
    """Multi-client batched inference over one :class:`predict.Predictor`.

    >>> with InferenceService(predictor, max_batch=8) as svc:
    ...     fut = svc.submit(image, points)          # non-blocking
    ...     mask = fut.result(timeout=5.0)           # (H, W) float32
    ...     mask2 = svc.predict(image2, points2)     # blocking convenience

    ``max_batch`` (power of two) tops the bucket ladder; ``queue_depth``
    bounds admission; ``max_wait_s`` bounds how long the batcher holds a
    lone request hoping for company; ``default_deadline_s`` applies to
    requests submitted without an explicit deadline (None = no deadline).
    ``strict_retrace=False`` keeps serving after a watchdog trip (counted
    and exposed, but not fatal).
    """

    #: substring of the predictor's jitted forward in compile logs
    _FORWARD_NAME = "forward"

    def __init__(self, predictor, max_batch: int = 8,
                 queue_depth: int = 64, max_wait_s: float = 0.005,
                 default_deadline_s: float | None = None,
                 strict_retrace: bool = True,
                 metrics: ServeMetrics | None = None,
                 trace: TraceCapture | None = None):
        if queue_depth < 1:
            raise ValueError(f"queue_depth must be >= 1, got {queue_depth}")
        if max_wait_s < 0:
            raise ValueError(f"max_wait_s must be >= 0, got {max_wait_s}")
        self.predictor = predictor
        self.buckets = batching.bucket_sizes(max_batch)
        self.max_batch = max_batch
        self.max_wait_s = max_wait_s
        self.default_deadline_s = default_deadline_s
        self.strict_retrace = strict_retrace
        self.metrics = metrics or ServeMetrics()
        self._queue: queue.Queue[_Request] = queue.Queue(maxsize=queue_depth)
        # mute_jax_logs=False: this watchdog stays open for the service's
        # LIFETIME — the default propagation pause would silence every jax
        # warning/error process-wide for as long as we serve
        self._watchdog = CompileWatchdog(match=self._FORWARD_NAME,
                                         mute_jax_logs=False)
        #: on-demand bounded device-trace trigger (telemetry.trace),
        #: armed by POST /debug/trace or SIGUSR2, driven by the worker
        self.trace = trace
        self._shapes_dispatched: set[tuple[int, ...]] = set()
        self._warm_shapes: set[tuple[int, ...]] = set()
        self._unhealthy: str | None = None
        self._stop = threading.Event()
        #: "new" (accepting, queued until start) -> "running" -> "stopped"
        self._state = "new"
        self._worker: threading.Thread | None = None

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "InferenceService":
        """Start the batcher worker.  Requests submitted BEFORE start sit
        in the queue and drain as the first batch — which is also how a
        deterministic multi-request batch is composed in tests."""
        if self._state != "new":
            raise RuntimeError(f"cannot start a {self._state} service")
        # chaos: arm an env-named fault plan (DPTPU_CHAOS_PLAN) for this
        # service's lifetime; one getenv when unset
        chaos_sites.maybe_arm_from_env()
        self._state = "running"
        self._worker = threading.Thread(target=self._run, name="serve-batcher",
                                        daemon=True)
        self._worker.start()
        return self

    def stop(self) -> None:
        """Stop the worker and fail any still-queued requests."""
        if self._state == "stopped":
            return
        self._state = "stopped"
        self._stop.set()
        if self._worker is not None:
            self._worker.join()
            self._worker = None
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            try:
                if req.future.set_running_or_notify_cancel():
                    req.future.set_exception(
                        ServiceUnhealthyError("service stopped"))
                    self.metrics.count("failed")
            except RuntimeError:
                # a racing submit() already failed its own future (the
                # post-put guard); never let one resolved future abort
                # the drain and strand the rest
                pass

    def __enter__(self) -> "InferenceService":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop()

    # ------------------------------------------------------------ front door

    def submit(self, image: np.ndarray, points: Any,
               deadline_s: float | None = None) -> Future:
        """Enqueue one request; returns a Future resolving to the mask.

        Host-side preprocessing runs here, on the caller's thread.  Raises
        :class:`QueueFullError` immediately when the bounded queue is full
        (shed, don't wait) and :class:`ServiceUnhealthyError` when the
        service is stopped or tripped unhealthy.  Bad inputs (malformed
        points, clicks outside the image) raise ``ValueError`` here,
        before anything is queued.
        """
        if self._state == "stopped":
            raise ServiceUnhealthyError("service stopped")
        if self._unhealthy and self.strict_retrace:
            raise ServiceUnhealthyError(self._unhealthy)
        # chaos seam, on the CALLER's thread: latency is a slow host
        # preprocess (builds queue pressure), an error is a front-door
        # dependency failing — both before anything is queued
        chaos_sites.fire("serve/enqueue")
        if self._queue.full():
            # fast-path shed BEFORE the (expensive) host preprocessing:
            # under overload a rejection must not cost nearly as much host
            # CPU as serving would.  Best-effort (racy by nature); the
            # put_nowait below is the authoritative check.
            self.metrics.count("shed_queue_full")
            raise QueueFullError(
                f"request queue full ({self._queue.maxsize} deep) — "
                "overloaded; retry with backoff")
        concat, bbox = self.predictor.prepare(image, points)
        now = time.perf_counter()
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        req = _Request(concat=concat, bbox=bbox,
                       shape_hw=tuple(np.asarray(image).shape[:2]),
                       future=Future(), submitted=now,
                       deadline=None if deadline_s is None
                       else now + deadline_s)
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            self.metrics.count("shed_queue_full")
            raise QueueFullError(
                f"request queue full ({self._queue.maxsize} deep) — "
                "overloaded; retry with backoff") from None
        self.metrics.count("requests")
        if self._state == "stopped" and not req.future.done():
            # raced a concurrent stop() past its queue drain: fail the
            # future now rather than strand the caller until their timeout
            try:
                if req.future.set_running_or_notify_cancel():
                    req.future.set_exception(
                        ServiceUnhealthyError("service stopped"))
            except RuntimeError:
                pass  # stop()'s drain got it first — already resolved
        return req.future

    def predict(self, image: np.ndarray, points: Any,
                deadline_s: float | None = None,
                timeout: float | None = None) -> np.ndarray:
        """Blocking convenience: :meth:`submit` + ``Future.result``."""
        return self.submit(image, points, deadline_s).result(timeout)

    def warmup(self) -> None:
        """Compile every bucket's program before taking traffic: a cold
        service otherwise charges its first unlucky clients the XLA
        compile — exactly the latency cliff the bucket ladder prevents.

        The warmed shapes are registered with the retrace tripwire: these
        compiles happen on the CALLING thread (invisible to the worker's
        thread-local watchdog), so without registration the budget would
        silently allow that many real steady-state retraces before
        tripping."""
        for shape in warmup_buckets(self.predictor, self.buckets):
            self._warm_shapes.add(self._compiled_shape(shape))

    # ------------------------------------------------------------ ops surface

    def health(self) -> dict:
        """Liveness + the counters a probe needs to decide 'still good'."""
        return {
            "ok": self._state == "running" and self._unhealthy is None,
            "running": self._state == "running",
            "state": self._state,
            "unhealthy_reason": self._unhealthy,
            "queue_depth": self._queue.qsize(),
            "queue_capacity": self._queue.maxsize,
            "buckets": list(self.buckets),
            "stats": self.metrics.snapshot(),
        }

    def audit_programs(self, buckets=None) -> dict:
        """``{serve_forward_b<N>: (fn, args)}`` for the EXACT jitted
        forward at each bucket's compiled shape (mesh padding included,
        :meth:`_compiled_shape`) — the hook jaxaudit (analysis.ir)
        traces and the checked-in serve contracts pin.  Args are
        ShapeDtypeStructs; tracing never dispatches."""
        import jax
        import jax.numpy as jnp

        h, w = self.predictor.resolution
        ch = getattr(self.predictor, "in_channels", 4)
        fn = self.predictor.forward_jitted
        return {
            f"serve_forward_b{b}": (fn, (jax.ShapeDtypeStruct(
                self._compiled_shape((b, h, w, ch)), jnp.float32),))
            for b in (buckets if buckets is not None else self.buckets)
        }

    def audit(self, buckets=None, **kwargs) -> dict:
        """jaxaudit reports for the bucket forwards (see analysis.ir)."""
        from ..analysis import ir as ir_lib

        return ir_lib.audit_many(self.audit_programs(buckets), **kwargs)

    @property
    def compile_counts(self) -> dict:
        """Forward-compile counts seen by the lifetime watchdog."""
        return dict(self._watchdog.counts)

    @property
    def buckets_compiled(self) -> set[int]:
        """Bucket sizes dispatched (== compiled, absent retraces)."""
        return {s[0] for s in self._shapes_dispatched}

    # ------------------------------------------------------------ worker

    def _run(self) -> None:
        # The watchdog must live on THIS thread: jax.log_compiles() is a
        # thread-local config context, and every forward dispatch (hence
        # every compile) happens here.  A watchdog entered on the caller's
        # thread would count nothing and silently disarm the retrace check.
        with self._watchdog:
            while not self._stop.is_set():
                batch = self._gather()
                if self.trace is not None:
                    # drive the on-demand capture from the worker (the
                    # only thread dispatching device work): 1 step per
                    # batch, 0 on idle polls so the wall-clock backstop
                    # still closes a capture when traffic stops
                    self.trace.tick(1 if batch else 0)
                if batch:
                    self._process(batch)
            if self.trace is not None:
                self.trace.close()

    def _gather(self) -> list[_Request]:
        """Drain on the max-wait/max-batch policy: dispatch when
        ``max_batch`` requests are pending OR ``max_wait_s`` has elapsed
        since the first one was picked up.  The window bounds WAITING for
        company only — requests already sitting in the queue are always
        drained (even at ``max_wait_s=0``), or a pre-loaded backlog would
        trickle out one lane at a time."""
        try:
            first = self._queue.get(timeout=0.05)
        except queue.Empty:
            return []
        batch = [first]
        wait_until = time.perf_counter() + self.max_wait_s
        while len(batch) < self.max_batch:
            remaining = wait_until - time.perf_counter()
            try:
                if remaining > 0:
                    batch.append(self._queue.get(timeout=remaining))
                else:
                    batch.append(self._queue.get_nowait())
            except queue.Empty:
                if remaining <= 0:
                    break
        return batch

    def _process(self, batch: list[_Request]) -> None:
        # chaos seam, on the WORKER thread before the deadline check:
        # injected latency stalls the whole drain exactly like a slow
        # device — queued deadlines expire (504 shed) and the bounded
        # queue backs up (429 shed), which is the degradation the
        # serve-latency scenario asserts instead of a crash.  A raised
        # fault fails THIS batch and the worker serves on (the same
        # fail-the-batch contract the forward's except clause keeps).
        try:
            chaos_sites.fire("serve/drain", batch_size=len(batch))
        except Exception as e:
            failed = 0
            for req in batch:
                if req.future.set_running_or_notify_cancel():
                    req.future.set_exception(e)
                    failed += 1
            self.metrics.count("failed", failed)
            return
        now = time.perf_counter()
        live: list[_Request] = []
        for req in batch:
            if not req.future.set_running_or_notify_cancel():
                continue                       # client gave up; skip the lane
            if req.deadline is not None and now > req.deadline:
                self.metrics.count("shed_deadline")
                req.future.set_exception(DeadlineExceededError(
                    "deadline passed while queued — the service is "
                    "saturated; shed instead of serving a stale answer"))
                continue
            live.append(req)
        if not live:
            return
        try:
            bucket = batching.bucket_for(len(live), self.buckets)
            padded = batching.pad_to_bucket(
                np.stack([r.concat for r in live]), bucket)
            probs = batching.unpad(self.predictor.forward_prepared(padded),
                                   len(live))
            # register AFTER a successful forward: a dispatch that dies
            # mid-compile must not leave a phantom shape that either
            # false-trips the tripwire on retry or pads its budget
            self._shapes_dispatched.add(self._compiled_shape(padded.shape))
            self._check_retrace()
            for i, req in enumerate(live):
                req.future.set_result(self.predictor.paste_back(
                    probs[i], req.bbox, req.shape_hw))
            self.metrics.observe_batch(bucket, len(live))
            self.metrics.count("completed", len(live))
            done = time.perf_counter()
            for req in live:
                self.metrics.observe_latency(done - req.submitted)
        except Exception as e:                       # fail the batch, serve on
            failed = 0
            for req in live:
                if not req.future.done():            # not the already-resolved
                    req.future.set_exception(e)
                    failed += 1
            self.metrics.count("failed", failed)

    def _compiled_shape(self, shape: tuple[int, ...]) -> tuple[int, ...]:
        """The shape the forward actually COMPILES for a bucket dispatch.

        A mesh predictor additionally pads the batch up to the data-axis
        extent inside ``forward_prepared`` (mesh.pad_to_multiple), which
        can collapse several buckets onto one program — keying the retrace
        check on the pre-mesh shape would over-count expected programs and
        desensitize the tripwire by exactly that margin."""
        mesh = getattr(self.predictor, "mesh", None)
        if mesh is None:
            return shape
        from ..parallel.mesh import DATA_AXIS
        m = mesh.shape[DATA_AXIS]
        return (-(-shape[0] // m) * m, *shape[1:])

    def _check_retrace(self) -> None:
        """One compile per bucket, ever: more forward compiles than
        distinct dispatched shapes means a steady-state retrace (shape
        drift, donation mismatch, tracer-dependent Python) — the failure
        jaxlint hunts statically, caught here at runtime.  Shapes warmed
        via :meth:`warmup` are excluded from the budget (their compiles
        happened off-worker, so dispatching them must cost ZERO watched
        compiles — the tripwire fires on the very first retrace)."""
        compiles = sum(self._watchdog.counts.values())
        budget = len(self._shapes_dispatched - self._warm_shapes)
        if compiles > budget:
            self.metrics.count("retrace_failures")
            self._unhealthy = (
                f"steady-state retrace: {compiles} forward compiles for "
                f"{budget} cold batch shapes "
                f"(counts: {dict(self._watchdog.counts)}) — run jaxlint")
