"""Zero-downtime checkpoint hot-swap: canary a new generation, never drop
a live session.

A long-running service must pick up retrained checkpoints without a
restart (a restart = every session re-encodes + a cold-compile cliff).
The mechanism is a **generation pool**:

* Every set of params is a *generation* (``Generation``): the initial
  predictor is generation 0.  :meth:`PredictorPool.begin_swap` loads a
  NEW predictor alongside the old (both resident — the HBM cost of a
  swap window is one extra param set) and marks it the *canary*.
* **Routing.**  New sessions and stateless requests hash (session id) or
  round-robin (stateless) into the canary with probability
  ``canary_fraction``; everything else stays on the active generation.
  EXISTING sessions are never re-routed: features encoded by generation
  N are only decodable by generation N's params, so a session sticks to
  its generation for life — that affinity is what makes the swap
  zero-downtime.
* **Decide.**  The service worker reports every request outcome via
  :meth:`observe`.  A non-finite output from the canary (NaN-poisoned
  checkpoint) rolls back immediately; an error rate above
  ``max_error_rate`` after ``min_observations`` rolls back; ``promote_after``
  clean observations promote automatically (set None to require a manual
  :meth:`promote` — the operator-gated posture).
* **Drain, then retire.**  After promote, the old generation is
  *draining*: it serves its remaining sessions' warm clicks until the
  store holds none and its in-flight count is zero, then the pool drops
  the last reference (params freed).  ``serve_params_generations_live``
  gauges the window; ``serve_swaps_total{outcome=promoted|rolled_back}``
  counts decisions.

The pool is predictor-agnostic glue: it never touches the session store
directly.  The service reacts to the action strings :meth:`observe`
returns (evicting canary sessions on rollback) — one direction of
dependency, no cycles.
"""

from __future__ import annotations

import threading
import zlib

from ..telemetry import events as events_lib

#: generation lifecycle states
STATES = ("active", "canary", "draining", "retired")


def load_swap_predictor(base_predictor, params, batch_stats,
                        model=None, **kwargs):
    """Build the swapped-in generation's predictor from restored params.

    THE seam new weights enter a live service through — every swap
    source (a training run's fresh best checkpoint, a torch import)
    funnels its restored ``params``/``batch_stats`` here, inheriting the
    serving configuration (resolution, relax, guidance family, ...) from
    the predictor already in service so the compiled-program ladder stays
    compatible.  The ``serve/swap_params`` chaos site fires on the param
    tree: a ``nan`` fault models a poisoned checkpoint arriving via
    hot-swap, which the canary health check must roll back
    (chaos scenario ``hot_swap_under_load``).
    """
    from ..chaos import sites as chaos_sites
    from ..predict import Predictor

    params = chaos_sites.fire("serve/swap_params", payload=params)
    model = model if model is not None else base_predictor.model
    for attr in ("resolution", "relax", "zero_pad", "alpha", "guidance",
                 "in_channels"):
        kwargs.setdefault(attr, getattr(base_predictor, attr))
    kwargs.setdefault("mesh", getattr(base_predictor, "mesh", None))
    return Predictor(model, params, batch_stats, **kwargs)


class Generation:
    """One resident param set + its health counters."""

    __slots__ = ("gen_id", "predictor", "label", "state",
                 "ok", "errors", "nonfinite", "inflight")

    def __init__(self, gen_id: int, predictor, label: str,
                 state: str = "active"):
        self.gen_id = gen_id
        self.predictor = predictor
        self.label = label
        self.state = state
        self.ok = 0
        self.errors = 0
        self.nonfinite = 0
        self.inflight = 0

    def snapshot(self) -> dict:
        return {"gen": self.gen_id, "label": self.label,
                "state": self.state, "ok": self.ok, "errors": self.errors,
                "nonfinite": self.nonfinite, "inflight": self.inflight}


class SwapInProgressError(RuntimeError):
    """begin_swap while a canary is still undecided — promote or roll
    back first (two undecided canaries would make error attribution and
    rollback targets ambiguous)."""


class PredictorPool:
    """Owns the predictor generations; thread-safe for the service's
    submit threads + worker."""

    def __init__(self, predictor, registry=None,
                 canary_fraction: float = 0.1,
                 min_observations: int = 20,
                 max_error_rate: float = 0.1,
                 promote_after: int | None = 50):
        from ..telemetry.registry import get_registry

        self._lock = threading.Lock()
        self._gens: dict[int, Generation] = {  # jaxrace: guarded-by=self._lock
            0: Generation(0, predictor, "initial", "active")}
        self._next_id = 1        # jaxrace: guarded-by=self._lock
        self._active = 0         # jaxrace: guarded-by=self._lock
        self._canary: int | None = None  # jaxrace: guarded-by=self._lock
        self._rr = 0  # stateless round-robin counter; jaxrace: guarded-by=self._lock
        self.canary_fraction = float(canary_fraction)
        self.min_observations = int(min_observations)
        self.max_error_rate = float(max_error_rate)
        self.promote_after = promote_after
        reg = registry or get_registry()
        self._c_swap = {
            outcome: reg.counter("serve_swaps_total",
                                 "hot-swap decisions",
                                 labels={"outcome": outcome})
            for outcome in ("promoted", "rolled_back")}
        self._base_swaps = {o: c.value for o, c in self._c_swap.items()}
        self._g_live = reg.gauge("serve_params_generations_live",
                                 "resident param generations")
        self._g_live.set(1.0)

    # ------------------------------------------------------------- routing

    @property
    def active_generation(self) -> int:
        with self._lock:
            return self._active

    @property
    def canary_generation(self) -> int | None:
        with self._lock:
            return self._canary

    @property
    def active_predictor(self):
        with self._lock:
            return self._gens[self._active].predictor

    def predictor_for(self, gen_id: int):
        with self._lock:
            return self._gens[gen_id].predictor

    def route(self, session_id: str | None) -> tuple[int, object]:
        """(generation id, predictor) for a NEW session or a stateless
        request.  Deterministic per session id (crc32 bucketing) so a
        session that re-encodes mid-canary lands on the same side it
        would have; stateless requests round-robin so a canary sees
        traffic even from a single chatty client."""
        with self._lock:
            gen = self._active
            if self._canary is not None:
                if session_id is None:
                    self._rr += 1
                    frac = (self._rr % 1000) / 1000.0
                else:
                    frac = (zlib.crc32(session_id.encode("utf-8"))
                            % 1000) / 1000.0
                if frac < self.canary_fraction:
                    gen = self._canary
            g = self._gens[gen]
            return gen, g.predictor

    def track_inflight(self, gen_id: int, delta: int) -> None:
        with self._lock:
            g = self._gens.get(gen_id)
            if g is not None:
                g.inflight += delta

    def is_resident(self, predictor) -> bool:
        """Does any live generation still hold ``predictor``?  The
        service uses this to drop ITS OWN base-predictor reference once
        the generation retires — otherwise the constructor's param set
        would stay pinned for the service's lifetime and every promote
        would permanently grow the resident footprint."""
        with self._lock:
            return any(g.predictor is predictor
                       for g in self._gens.values())

    # ---------------------------------------------------------------- swap

    def begin_swap(self, predictor, label: str = "",
                   canary_fraction: float | None = None) -> int:
        """Admit a new generation as the canary; returns its id.  The new
        predictor must already be constructed (params resident) — loading
        is the caller's move, so a failed restore can never leave the
        pool half-swapped."""
        with self._lock:
            if self._canary is not None:
                raise SwapInProgressError(
                    f"generation {self._canary} is still canarying — "
                    "promote() or rollback() before swapping again")
            gen_id = self._next_id
            self._next_id += 1
            self._gens[gen_id] = Generation(
                gen_id, predictor, label or f"swap-{gen_id}", "canary")
            self._canary = gen_id
            if canary_fraction is not None:
                self.canary_fraction = float(canary_fraction)
            self._publish()
            # flight recorder: the canary episode's opening anchor
            events_lib.emit("serve", "swap_admit",
                            payload={"gen_id": gen_id,
                                     "label": self._gens[gen_id].label,
                                     "canary_fraction":
                                         self.canary_fraction})
            return gen_id

    def observe(self, gen_id: int, ok: bool,
                nonfinite: bool = False) -> str | None:
        """Record one request outcome; returns the decision it triggered
        (``'promoted'`` | ``'rolled_back'``) or None.  The service calls
        this from the worker after every resolved request and reacts to
        the action (rollback -> evict that generation's sessions)."""
        with self._lock:
            g = self._gens.get(gen_id)
            if g is None:
                return None
            if ok and not nonfinite:
                g.ok += 1
            else:
                g.errors += 1
                if nonfinite:
                    g.nonfinite += 1
            if gen_id != self._canary:
                return None
            # decision table, most urgent first
            if g.nonfinite:
                return self._rollback_locked()
            total = g.ok + g.errors
            if (total >= self.min_observations
                    and g.errors / total > self.max_error_rate):
                return self._rollback_locked()
            if (self.promote_after is not None
                    and g.ok >= self.promote_after
                    and (total == 0
                         or g.errors / total <= self.max_error_rate)):
                return self._promote_locked()
            return None

    def promote(self) -> dict:
        """Manually promote the canary to active (old active drains)."""
        with self._lock:
            if self._canary is None:
                raise RuntimeError("no canary generation to promote")
            self._promote_locked()
            return self.snapshot_locked()

    def rollback(self) -> dict:
        """Manually roll the canary back (its sessions must be evicted by
        the caller — see :meth:`observe`'s contract)."""
        with self._lock:
            if self._canary is None:
                raise RuntimeError("no canary generation to roll back")
            self._rollback_locked()
            return self.snapshot_locked()

    def gc(self, sessions_by_generation: dict[int, int]) -> list[int]:
        """Retire drained generations: draining/retired, no live sessions
        in the store, nothing in flight.  Returns the ids whose params
        were just released."""
        freed = []
        with self._lock:
            for gen_id, g in list(self._gens.items()):
                if gen_id in (self._active, self._canary):
                    continue
                if (g.inflight == 0
                        and sessions_by_generation.get(gen_id, 0) == 0
                        and g.predictor is not None):
                    g.predictor = None  # params freed with the last ref
                    g.state = "retired"
                    freed.append(gen_id)
            if freed:
                self._publish()
        return freed

    # ---------------------------------------------------------------- ops

    def swaps(self) -> dict:
        """{'promoted': n, 'rolled_back': n} since pool construction."""
        return {o: int(c.value - self._base_swaps[o])
                for o, c in self._c_swap.items()}

    def snapshot(self) -> dict:
        with self._lock:
            return self.snapshot_locked()

    def snapshot_locked(self) -> dict:
        return {
            "active": self._active,
            "canary": self._canary,
            "canary_fraction": self.canary_fraction,
            "swaps": {o: int(c.value - self._base_swaps[o])
                      for o, c in self._c_swap.items()},
            "generations": [g.snapshot()
                            for _, g in sorted(self._gens.items())],
        }

    # ------------------------------------------------------------ internals

    def _promote_locked(self) -> str:
        old_active = self._gens[self._active]
        gen = self._gens[self._canary]
        gen.state = "active"
        self._active = self._canary
        self._canary = None
        old_active.state = "draining"
        self._c_swap["promoted"].inc()
        self._publish()
        events_lib.emit("serve", "swap_promote",
                        payload={"gen_id": gen.gen_id, "label": gen.label,
                                 "ok": gen.ok, "errors": gen.errors})
        return "promoted"

    def _rollback_locked(self) -> str:
        g = self._gens[self._canary]
        g.state = "draining"   # in-flight canary work still needs params
        self._canary = None
        self._c_swap["rolled_back"].inc()
        self._publish()
        events_lib.emit("serve", "swap_rollback",
                        payload={"gen_id": g.gen_id, "label": g.label,
                                 "ok": g.ok, "errors": g.errors,
                                 "nonfinite": g.nonfinite})
        return "rolled_back"

    def _publish(self) -> None:
        self._g_live.set(float(sum(
            1 for g in self._gens.values() if g.predictor is not None)))
