"""serve: TPU-native batched inference service for click-guided segmentation.

``predict.Predictor`` answers one caller; this package answers many
concurrent ones from the same compiled forward — the ROADMAP's
"heavy traffic" leg of the inference story.  Architecture (details in
docs/DESIGN.md "Serving"):

    clients -> bounded queue -> max-wait/max-batch drain -> power-of-two
    bucket padding -> ONE compiled program per bucket -> unpad ->
    per-request paste-back -> futures

* :mod:`batching` — the pure bucket/pad/unpad shape math
* :mod:`service` — :class:`InferenceService`: queue, worker, deadlines,
  load shedding, CompileWatchdog retrace tripwire, metrics
* :mod:`sessions` — :class:`SessionStore`: the per-session on-device
  encoder cache (TTL + LRU under an HBM byte budget) behind warm clicks
* :mod:`swap` — :class:`PredictorPool`: zero-downtime checkpoint
  hot-swap with canary routing, promote/rollback, generation draining
* :mod:`quantize` — :class:`QuantizedPredictor`: post-training
  per-channel int8 weight quantization of the serve forward, declared
  via :class:`QuantPolicy` and policed by jaxaudit JA002
* :mod:`aot` — :class:`AotCache`: pre-compiled, serialized bucket-ladder
  executables (``dptpu-aot``) for near-zero cold start, crc-verified
  with loud fresh-compile fallback
* :mod:`metrics` — counters + p50/p99 request latency (ops surface)
* :mod:`client` — :class:`ServeClient` over in-process or HTTP targets
* :mod:`router` — :class:`HashRing` + :func:`least_loaded`: the fleet's
  pure routing math (consistent hash with virtual nodes for session
  affinity; queue/p99 ordering for stateless requests)
* :mod:`fleet` — :class:`FleetFront` (``dptpu-fleet``): the
  multi-replica front — replica registry/state machine, health-driven
  ring membership, one-shot failover, process supervision in ``local``
  mode, and the ``/fleet/plan`` autoscale surface
* :mod:`__main__` — ``python -m distributedpytorch_tpu.serve`` HTTP shell

>>> from distributedpytorch_tpu.serve import InferenceService
>>> with InferenceService(predictor, max_batch=8) as svc:
...     mask = svc.predict(image, points)       # == Predictor.predict's
"""

from .aot import AotCache, AotCacheError, AotCacheMiss
from .batching import bucket_for, bucket_sizes, pad_to_bucket, unpad
from .client import (
    HealthCache,
    ReplicaDrainingError,
    ServeClient,
    decode_array,
    encode_array,
)
from .fleet import AutoscaleGovernor, FleetFront, FleetRegistry, scale_plan
from .metrics import ServeMetrics
from .quantize import (
    QTensor,
    QuantizedPredictor,
    QuantPolicy,
    quant_policy,
    quantization_block,
    quantize_predictor,
)
from .service import (
    DeadlineExceededError,
    InferenceService,
    QueueFullError,
    ServiceUnhealthyError,
    SessionLaneFullError,
    warmup_buckets,
)
from .router import HashRing, least_loaded
from .sessions import Session, SessionStore
from .swap import PredictorPool, SwapInProgressError

__all__ = [
    "AotCache",
    "AotCacheError",
    "AotCacheMiss",
    "AutoscaleGovernor",
    "DeadlineExceededError",
    "FleetFront",
    "FleetRegistry",
    "HashRing",
    "HealthCache",
    "InferenceService",
    "PredictorPool",
    "QTensor",
    "QuantPolicy",
    "QuantizedPredictor",
    "QueueFullError",
    "ReplicaDrainingError",
    "ServeClient",
    "ServeMetrics",
    "ServiceUnhealthyError",
    "Session",
    "SessionLaneFullError",
    "SessionStore",
    "SwapInProgressError",
    "bucket_for",
    "bucket_sizes",
    "decode_array",
    "encode_array",
    "least_loaded",
    "pad_to_bucket",
    "quant_policy",
    "scale_plan",
    "quantization_block",
    "quantize_predictor",
    "unpad",
    "warmup_buckets",
]
