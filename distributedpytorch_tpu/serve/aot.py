"""AOT executable cache: pre-compile the bucket ladder, boot replicas warm.

``--warmup`` is *the* scale-out latency tax: every replica boot re-pays
full XLA compilation for the whole bucket ladder (seconds per program on
CPU, tens of seconds per program for the 512px TPU ladder) before it can
take traffic.  The programs are identical across replicas — same
weights, same shapes, same jaxlib — so the compile belongs OFFLINE:

    dptpu-aot --cache-dir CACHE --run-dir RUN      # once, anywhere
    dptpu-serve --run-dir RUN --warmup --aot-cache CACHE   # every boot

``build`` lowers + compiles each ladder program (``jax.jit(...)
.lower().compile()``), serializes the executable
(``jax.experimental.serialize_executable``) and writes one file per
program plus a manifest.  A warm boot deserializes instead of
compiling — CompileWatchdog-verified ZERO compiles — and installs each
executable into the predictor's per-shape AOT table
(:meth:`predict.Predictor.install_aot`).

Trust is explicit, never assumed:

* **the manifest is written atomically LAST** (the packed-data idiom:
  tmp + fsync + ``os.replace``) — a crashed build leaves NO manifest,
  never a half-trusted one;
* **every entry carries a crc32** over its serialized bytes, re-checked
  on every load (and by ``dptpu-aot --verify``): a torn or bit-rotted
  entry is a typed :class:`AotCacheError`, and the boot falls back
  LOUDLY to a fresh compile — degraded cold start, never a corrupt
  executable taking traffic;
* **the cache key is the full identity of the compiled program**:
  jax + jaxlib versions, platform, the live topology fingerprint
  (parallel/plan.topology_fingerprint — XLA executables are
  device-assignment-bound), resolution/channels/split shape, the
  quantization regime, and a digest of the served weights (the
  executable BAKES the params as constants, so an entry built from
  checkpoint A must never serve checkpoint B's boot).  Any mismatch is
  a typed :class:`AotCacheMiss` naming the differing keys — fresh
  compile, loud line, service boots anyway.

The deserialization gotcha (root-caused in analysis/ir.py): a
deserialized executable reports ZEROED memory stats, so anything that
audits or cost-models a program must do it from the LOWERED form at
build time — which is exactly what ``build`` does by sharing the
:mod:`telemetry.lowering` cache with jaxaudit, never from the
executable a warm boot loads.

TRUST BOUNDARY: the crc32 detects *rot* (torn writes, bit flips), not
*tampering* — entries deserialize via pickle, and the checksum lives in
the same directory as the bytes it covers, so anyone who can WRITE the
cache dir can execute code in every replica that boots from it.  Treat
the cache directory with exactly the trust you give the checkpoint
itself (same filesystem ACLs, same provenance); never point a boot at a
cache dir less trusted than the weights.
"""

from __future__ import annotations

import json
import os
import pickle
import sys
import zlib

import numpy as np

from ..chaos import sites as chaos_sites

MANIFEST = "manifest.json"

#: manifest schema version — bump on layout changes so an old cache
#: misses loudly instead of unpickling garbage
CACHE_VERSION = 1


class AotCacheMiss(KeyError):
    """No usable entry: absent cache/manifest/program, or a fingerprint
    mismatch (different jaxlib/topology/weights/...).  Expected in
    normal operation — the caller compiles fresh and says so."""

    def __str__(self) -> str:  # KeyError quotes its arg; keep it prose
        return self.args[0] if self.args else ""


class AotCacheError(RuntimeError):
    """A PRESENT entry that cannot be trusted: checksum mismatch, torn
    file, undeserializable payload.  The caller must fall back loudly —
    and never execute the bytes."""


def params_fingerprint(predictor) -> str:
    """sha256 over the served weight bytes (params + batch stats) — the
    piece of the cache key that pins WHICH checkpoint the executable
    baked.  Quantized trees digest their int8/scale buffers (QTensor is
    a pytree node), so f32 and int8 forms of one checkpoint never
    collide."""
    from ..train.checkpoint import param_digest

    return param_digest({"params": predictor.params,
                         "batch_stats": predictor.batch_stats})


def cache_fingerprint(predictor) -> dict:
    """The full identity a cache entry is only valid under.  Every field
    is load-bearing: executables are jaxlib-serialization-format-bound,
    platform- and device-assignment-bound, shape-bound, and bake the
    (possibly quantized) weights as constants."""
    import jax
    import jaxlib

    from ..parallel.plan import topology_fingerprint
    from .quantize import quantization_block

    return {
        "cache_version": CACHE_VERSION,
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": jax.devices()[0].platform,
        "topology": topology_fingerprint(),
        "resolution": list(predictor.resolution),
        "in_channels": int(getattr(predictor, "in_channels", 4)),
        "split": bool(getattr(predictor, "supports_sessions", False)),
        "quantization": quantization_block(
            getattr(predictor, "quant_policy", None)),
        "params_digest": params_fingerprint(predictor),
    }


def fingerprint_mismatch(saved: dict, live: dict) -> list[str]:
    """The keys on which two fingerprints disagree (empty = compatible).
    Compared key-by-key so the miss message NAMES what moved — 'topology:
    cpu:8/p1 != tpu:4/p1' routes the operator straight to the fix."""
    keys = sorted(set(saved) | set(live))
    return [f"{k}: cached {saved.get(k)!r} != live {live.get(k)!r}"
            for k in keys if saved.get(k) != live.get(k)]


def ladder_programs(predictor, buckets) -> list[tuple]:
    """``[(name, fn, args, install_key), ...]`` — the bucket ladder's
    compiled-program inventory for one predictor (the same programs
    ``InferenceService.warmup`` compiles): per bucket, one whole
    forward for a stem predictor, encode + decode for a split one."""
    import jax
    import jax.numpy as jnp

    h, w = predictor.resolution
    ch = int(getattr(predictor, "in_channels", 4))
    sds = jax.ShapeDtypeStruct
    out = []
    if getattr(predictor, "supports_sessions", False):
        feats1 = predictor.feature_struct(1)
        for b in buckets:
            out.append((f"encode_b{b}", predictor.encode_jitted,
                        (sds((b, h, w, ch - 1), jnp.float32),),
                        ("encode", b)))
            out.append((f"decode_b{b}", predictor.decode_jitted,
                        (sds((b, *feats1.shape[1:]), feats1.dtype),
                         sds((b, h, w, 1), jnp.float32)),
                        ("decode", b)))
    else:
        for b in buckets:
            shape = (b, h, w, ch)
            out.append((f"forward_b{b}", predictor.forward_jitted,
                        (sds(shape, jnp.float32),), ("forward", shape)))
    return out


class AotCache:
    """One cache directory: entry files + the atomically-written manifest.

    ``verify`` and ``manifest`` are pure stdlib (zlib/json) — the
    ``dptpu-aot --verify`` sweep never initializes a jax backend.
    ``build``/``load`` touch jax (lower/compile, deserialize)."""

    def __init__(self, cache_dir: str):
        self.cache_dir = str(cache_dir)

    # ---------------------------------------------------------- manifest

    def manifest_path(self) -> str:
        return os.path.join(self.cache_dir, MANIFEST)

    def manifest(self) -> dict:
        """The parsed manifest.  Missing -> :class:`AotCacheMiss` (a
        cache that was never built, or whose build crashed pre-commit);
        unparseable -> :class:`AotCacheError` (the atomic write makes a
        torn manifest a corruption signal, not a crash artifact)."""
        try:
            with open(self.manifest_path(), encoding="utf-8") as f:
                raw = f.read()
        except OSError:
            raise AotCacheMiss(
                f"no AOT manifest at {self.manifest_path()} — build one "
                "with `dptpu-aot --cache-dir ...`") from None
        try:
            man = json.loads(raw)
            if not isinstance(man.get("entries"), dict) \
                    or not isinstance(man.get("fingerprint"), dict):
                raise ValueError("manifest missing entries/fingerprint")
            for name, ent in man["entries"].items():
                # schema-validate every entry record here, so a
                # valid-JSON-but-mangled manifest stays inside the typed
                # fallback contract (load/verify index into these fields
                # — an unvalidated TypeError there would escape the
                # warmup's miss/error handling and kill the boot)
                if (not isinstance(ent, dict)
                        or not isinstance(ent.get("file"), str)
                        or not isinstance(ent.get("bytes"), int)
                        or not isinstance(ent.get("crc32"), int)):
                    raise ValueError(
                        f"entry {name!r} malformed (want file/bytes/"
                        f"crc32, got {ent!r})")
        except ValueError as e:
            raise AotCacheError(
                f"unreadable AOT manifest {self.manifest_path()}: {e} — "
                "rebuild the cache") from None
        return man

    # ------------------------------------------------------------- build

    def build(self, predictor, buckets) -> dict:
        """Pre-compile + serialize the whole ladder; returns a summary.

        Lowers through the shared :mod:`telemetry.lowering` cache (one
        lower per program per process, shared with jaxaudit — the audit
        of these exact programs happens from the LOWERED form here, not
        from a deserialized executable whose memory stats are zeroed).
        """
        import jax
        from jax.experimental import serialize_executable

        from ..telemetry.lowering import lower_cached

        if getattr(predictor, "mesh", None) is not None:
            raise ValueError(
                "AotCache.build: mesh predictors compile GSPMD programs "
                "bound to this process's device assignment — the AOT "
                "cache serves single-device replicas")
        fingerprint = cache_fingerprint(predictor)
        os.makedirs(self.cache_dir, exist_ok=True)
        entries: dict[str, dict] = {}
        total = 0
        # THIS cache is the persistence layer: an executable that jax's
        # own persistent compilation cache deserialized re-serializes
        # into a blob that cannot deserialize again (its backend symbol
        # table is gone), so the build must compile genuinely fresh.
        # Flipping jax_enable_compilation_cache alone is NOT enough —
        # two jax-internal caches defeat it:
        #   1. compilation_cache.is_cache_used() LATCHES its answer at
        #      the first compile of the process; reset_cache() drops the
        #      latch so the disabled flag actually reaches the read path;
        #   2. Lowered.compile() consults an in-memory executable memo
        #      which may hold an executable an EARLIER (cache-enabled)
        #      compile deserialized from disk; clear_caches() drops it.
        # lower_cached's memo is our own and survives clear_caches(), so
        # lowering still shares the process-wide cache — only the
        # compile pays again.
        from jax._src import compilation_cache as _jax_cc

        cache_flag = jax.config.jax_enable_compilation_cache
        jax.config.update("jax_enable_compilation_cache", False)
        _jax_cc.reset_cache()
        jax.clear_caches()
        try:
            for name, fn, args, _key in ladder_programs(predictor,
                                                        buckets):
                compiled = lower_cached(fn, *args).lowered.compile()
                payload, in_tree, out_tree = \
                    serialize_executable.serialize(compiled)
                try:
                    # round-trip proof at build time: a blob that cannot
                    # deserialize HERE would poison every warm boot; any
                    # residual cache-bypass leak must fail the build
                    serialize_executable.deserialize_and_load(
                        payload, in_tree, out_tree)
                except Exception as e:
                    raise AotCacheError(
                        f"freshly built executable {name!r} does not "
                        f"survive a serialization round-trip "
                        f"({type(e).__name__}: {e}) — refusing to "
                        "commit a cache no boot could load") from e
                blob = pickle.dumps((payload, in_tree, out_tree),
                                    protocol=pickle.HIGHEST_PROTOCOL)
                fname = f"{name}.exec"
                path = os.path.join(self.cache_dir, fname)
                tmp = path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(blob)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
                entries[name] = {"file": fname, "bytes": len(blob),
                                 "crc32": zlib.crc32(blob)}
                total += len(blob)
        finally:
            jax.config.update("jax_enable_compilation_cache",
                              cache_flag)
            # drop the latch again so the NEXT compile re-evaluates the
            # restored flag — without this, the build's disabled answer
            # would stay latched and the rest of the process would skip
            # the persistent cache entirely
            _jax_cc.reset_cache()
        # the manifest commits the cache as a unit, atomically and LAST
        # — a build that dies above leaves entry files but no manifest,
        # and a manifest-less directory is a MISS, never a half-trust
        from ..train.checkpoint import atomic_write_json

        atomic_write_json(self.manifest_path(),
                          {"version": CACHE_VERSION,
                           "fingerprint": fingerprint,
                           "entries": entries})
        return {"cache_dir": self.cache_dir,
                "programs": sorted(entries),
                "bytes": total,
                "fingerprint": fingerprint}

    # -------------------------------------------------------------- load

    def load(self, name: str, fingerprint: dict):
        """One entry -> a live ``jax.stages.Compiled``.

        Raises :class:`AotCacheMiss` (absent / fingerprint mismatch,
        message naming every differing key) or :class:`AotCacheError`
        (present but untrustworthy: crc mismatch, undeserializable).
        The ``serve/aot_load`` chaos seam fires on the raw bytes BEFORE
        the checksum gate — an injected bitflip must surface as the
        typed checksum failure, proving rot cannot reach execution."""
        from jax.experimental import serialize_executable

        man = self.manifest()
        mismatch = fingerprint_mismatch(man["fingerprint"], fingerprint)
        if mismatch:
            raise AotCacheMiss(
                "AOT cache fingerprint mismatch — the cached executables "
                "were built for a different "
                + "; ".join(mismatch))
        ent = man["entries"].get(name)
        if ent is None:
            raise AotCacheMiss(
                f"no cached executable for program {name!r} "
                f"(cache holds: {sorted(man['entries'])})")
        path = os.path.join(self.cache_dir, ent["file"])
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError as e:
            raise AotCacheMiss(
                f"cached executable file missing for {name!r}: {e}") \
                from None
        # chaos seam: bit rot between disk and deserialization.  The
        # payload rides as a uint8 view; a bitflip fault returns a
        # private flipped copy which the crc gate below MUST catch.
        arr = np.frombuffer(data, dtype=np.uint8)
        fired = chaos_sites.fire("serve/aot_load", payload=arr,
                                 name=name, path=path)
        if fired is not arr:
            data = fired.tobytes()
        if len(data) != int(ent["bytes"]) \
                or zlib.crc32(data) != int(ent["crc32"]):
            raise AotCacheError(
                f"checksum mismatch for cached executable {name!r} "
                f"({path}): {len(data)} bytes crc {zlib.crc32(data)} vs "
                f"manifest {ent['bytes']} bytes crc {ent['crc32']} — "
                "torn write or bit rot; rebuild with dptpu-aot (or "
                "delete the cache dir)")
        try:
            payload, in_tree, out_tree = pickle.loads(data)
            return serialize_executable.deserialize_and_load(
                payload, in_tree, out_tree)
        except Exception as e:
            raise AotCacheError(
                f"cached executable {name!r} failed to deserialize "
                f"({type(e).__name__}: {e}) — stale serialization "
                "format or corruption; rebuild with dptpu-aot") from e

    # ------------------------------------------------------------ verify

    def verify(self) -> dict:
        """Re-checksum every entry (pure zlib — no jax, no backend).
        Returns ``{"entries": n, "bad": [...], "missing": [...]}``;
        ``bad`` names entries whose bytes no longer match their
        manifest crc, ``missing`` entries whose file is gone."""
        man = self.manifest()
        bad: list[str] = []
        missing: list[str] = []
        for name, ent in sorted(man["entries"].items()):
            path = os.path.join(self.cache_dir, ent["file"])
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError:
                missing.append(name)
                continue
            if len(data) != int(ent["bytes"]) \
                    or zlib.crc32(data) != int(ent["crc32"]):
                bad.append(name)
        return {"entries": len(man["entries"]), "bad": bad,
                "missing": missing,
                "fingerprint": man.get("fingerprint")}


# ------------------------------------------------------------------- CLI

def main(argv: list[str] | None = None, predictor=None) -> int:
    """``dptpu-aot``: build or verify an AOT executable cache.

    Build (default): ``dptpu-aot --cache-dir C --run-dir RUN
    [--max-batch 8] [--quantize int8]`` — pre-compiles the exact ladder
    ``dptpu-serve --run-dir RUN --max-batch 8 [--quantize int8]`` would
    compile at boot.  Verify: ``dptpu-aot --cache-dir C --verify``
    re-checksums every entry, exit non-zero naming bad ones (pure
    host-side sweep, safe on a box with no accelerator).

    ``predictor`` injects a prebuilt predictor (tests drive the build
    path without a training run on disk)."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="dptpu-aot",
        description="Pre-compile (and verify) the serve bucket ladder's "
                    "AOT executable cache — near-zero cold start for "
                    "`dptpu-serve --warmup --aot-cache`.")
    parser.add_argument("--cache-dir", required=True,
                        help="cache directory (entry files + manifest)")
    parser.add_argument("--verify", action="store_true",
                        help="re-checksum every cache entry instead of "
                             "building; exit non-zero naming bad entries")
    src = parser.add_mutually_exclusive_group()
    src.add_argument("--run-dir",
                     help="training run dir to build the ladder from")
    src.add_argument("--torch", metavar="PTH",
                     help="torch state_dict checkpoint instead of a run")
    parser.add_argument("--max-batch", type=int, default=8,
                        help="top micro-batch bucket (power of two) — "
                             "must match the serving config")
    parser.add_argument("--quantize", choices=("int8", "none"),
                        default=None,
                        help="quantization regime to build for (default: "
                             "the run config's model.quantization)")
    args = parser.parse_args(argv)

    cache = AotCache(args.cache_dir)
    if args.verify:
        try:
            report = cache.verify()
        except (AotCacheMiss, AotCacheError) as e:
            print(f"dptpu-aot: {e}", file=sys.stderr)
            return 2
        print(json.dumps(report, indent=1, sort_keys=True))
        if report["bad"] or report["missing"]:
            print(f"dptpu-aot: {len(report['bad'])} corrupt + "
                  f"{len(report['missing'])} missing entr(ies): "
                  f"{report['bad'] + report['missing']} — rebuild the "
                  "cache (a serve boot would fall back to fresh "
                  "compiles)", file=sys.stderr)
            return 1
        print(f"dptpu-aot: {report['entries']} entr(ies) verified",
              file=sys.stderr)
        return 0

    if predictor is None:
        if not (args.run_dir or args.torch):
            parser.error("build needs --run-dir or --torch "
                         "(or pass --verify)")
        from ..backend_health import pin_requested_platform

        pin_requested_platform()
        from .__main__ import build_predictor

        predictor = build_predictor(args)
    from .batching import bucket_sizes

    summary = cache.build(predictor, bucket_sizes(args.max_batch))
    print(json.dumps(summary, indent=1, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
