"""Session-log sink: the serve worker's crash-safe example recorder.

The flywheel's capture leg (ROADMAP item 5): the request path already
computed everything a training example needs — the relax-padded resized
crop (``concat``'s RGB channels), the click points, the content digest
(``serve/sessions.py:image_digest``, hashed once on the submit thread),
and the mask the user accepted — so logging one is a memcpy, not a
pipeline.  Records land in the packed idiom ``data/sessions.py`` owns
(blob + fixed-dtype index row + crc32), with ``meta.json`` committed
atomically LAST on each flush: readers trust meta's counts only, so a
sink crash mid-append is an invisible tail, never a torn record.

Worker-thread discipline (the reason this module is numpy + stdlib
only): ``offer`` runs on the serve worker between dispatches, so it must
never touch a device, block on I/O syncs, or re-hash pixels — appends
are buffered writes under one lock, dedup is an integer-set lookup off
the digests the submit thread already paid for, and ``flush`` (the meta
commit) rides the worker's existing 1 Hz housekeeping tick.

Budget + dedup outcomes book as the
``serve_session_log_{appended,deduped,dropped}_total`` counter family on
the process registry (dropped carries ``reason=budget|no_crop``), so
``/metrics`` and ``health()`` expose the flywheel's intake rate.
"""

from __future__ import annotations

import collections
import os
import threading
import zlib

import numpy as np

from ..chaos import sites as chaos_sites
from ..data.packed import BIN_NAME, INDEX_NAME, META_NAME
from ..data.sessions import SESSION_INDEX_DTYPE, dedup_key, encode_blob, \
    session_meta, write_meta
from ..telemetry.registry import MetricsRegistry, get_registry

#: dropped-counter reasons: over the byte/record budget, or a warm
#: (refinement) click whose cold crop already left the LRU
DROP_REASONS = ("budget", "no_crop")

#: cold crops kept for warm-click appends (session_id -> crop); sized so
#: a burst of interleaved sessions doesn't thrash, small enough that the
#: sink's host-memory cost stays invisible next to the batcher's
_CROP_CACHE = 64


class SessionLogSink:
    """Append-only packed-idiom writer for accepted (crop, clicks, mask)
    examples.

    * ``offer(req, prob)`` — the worker-path entry: derives the example
      from a completed request (cold requests carry the crop in
      ``req.concat``; warm ones resolve it from a small LRU the cold
      append populated) and appends it.
    * ``append(...)`` — the direct form tests and tools call.
    * dedup by ``(image digest, click bytes)`` — the submit thread's
      digest, re-hashed never; stateless requests (digest 0) fall back
      to a crc32 of the crop bytes.
    * ``flush()`` commits meta atomically (tmp + ``os.replace``); until
      then new records are an uncommitted tail readers ignore.
    * reopening an existing log resumes it: the committed prefix is
      kept, its dedup keys reloaded, any uncommitted tail truncated.
    """

    def __init__(self, path: str, *, resolution, guidance: str,
                 alpha: float, relax: int, zero_pad: bool,
                 max_bytes: int = 512 << 20, max_records: int = 100_000,
                 registry: MetricsRegistry | None = None):
        self.path = path
        self.resolution = (int(resolution[0]), int(resolution[1]))
        self.guidance = str(guidance)
        self.alpha = float(alpha)
        self.relax = int(relax)
        self.zero_pad = bool(zero_pad)
        self.max_bytes = int(max_bytes)
        self.max_records = int(max_records)
        self._lock = threading.Lock()
        self._crops: collections.OrderedDict[str, np.ndarray] = \
            collections.OrderedDict()
        self._dedup: set[int] = set()
        self._appended = 0
        self._deduped = 0
        self._dropped = {r: 0 for r in DROP_REASONS}
        self._dirty = False
        reg = registry or get_registry()
        self._c_appended = reg.counter(
            "serve_session_log_appended_total",
            "session examples appended to the flywheel log")
        self._c_deduped = reg.counter(
            "serve_session_log_deduped_total",
            "session examples skipped as content duplicates")
        self._c_dropped = {
            reason: reg.counter(
                "serve_session_log_dropped_total",
                "session examples dropped un-logged",
                labels={"reason": reason})
            for reason in DROP_REASONS}
        os.makedirs(path, exist_ok=True)
        self._resume_or_init()

    # ------------------------------------------------------------ lifecycle
    def _resume_or_init(self) -> None:
        """Open the bin/idx handles.  An existing meta.json resumes the
        committed log (parameters must match — a sink writing a
        different geometry into an old log would poison replay);
        anything past meta's counts (or with no meta at all) is an
        uncommitted tail, truncated away."""
        import json

        meta_path = os.path.join(self.path, META_NAME)
        n, bin_bytes_committed = 0, 0
        if os.path.isfile(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
            expect = session_meta(
                resolution=self.resolution, guidance=self.guidance,
                alpha=self.alpha, relax=self.relax, zero_pad=self.zero_pad,
                n_records=meta.get("n_records", 0),
                bin_bytes=meta.get("bin_bytes", 0),
                index_crc32=meta.get("index_crc32", 0))
            drift = {k for k in ("format", "kind", "resolution",
                                 "guidance", "alpha", "relax", "zero_pad")
                     if meta.get(k) != expect[k]}
            if drift:
                raise ValueError(
                    f"session log at {self.path} was written with "
                    f"different parameters ({sorted(drift)}) — point "
                    "--session-log at a fresh directory")
            n = int(meta["n_records"])
            bin_bytes_committed = int(meta["bin_bytes"])
        idx_path = os.path.join(self.path, INDEX_NAME)
        bin_path = os.path.join(self.path, BIN_NAME)
        committed = b""
        if n and os.path.isfile(idx_path):
            with open(idx_path, "rb") as f:
                committed = f.read(n * SESSION_INDEX_DTYPE.itemsize)
            rows = np.frombuffer(committed, SESSION_INDEX_DTYPE)
            self._dedup = {int(r["dedup"]) for r in rows}
        # truncate-to-committed, then append from there
        with open(idx_path, "wb") as f:
            f.write(committed)
        with open(bin_path, "ab") as f:
            f.truncate(bin_bytes_committed)
        self._idx = open(idx_path, "ab")
        self._bin = open(bin_path, "ab")
        self._n_records = n
        self._bin_bytes = bin_bytes_committed
        self._index_crc = (zlib.crc32(committed) & 0xFFFFFFFF
                           if committed else 0)
        if n == 0:
            # commit the empty log now: "sink on, no examples yet" must
            # read as a valid (empty) log, not as no-log
            self.flush(force=True)

    def flush(self, force: bool = False) -> None:
        """Commit everything appended so far: flush the data handles,
        then write meta atomically LAST — the ordering that makes every
        reader's view a prefix of committed records."""
        with self._lock:
            if not self._dirty and not force:
                return
            self._bin.flush()
            self._idx.flush()
            write_meta(self.path, session_meta(
                resolution=self.resolution, guidance=self.guidance,
                alpha=self.alpha, relax=self.relax,
                zero_pad=self.zero_pad, n_records=self._n_records,
                bin_bytes=self._bin_bytes, index_crc32=self._index_crc))
            self._dirty = False

    def close(self) -> None:
        self.flush()
        with self._lock:
            self._bin.close()
            self._idx.close()

    # ------------------------------------------------------------- appending
    def offer(self, req, prob: np.ndarray) -> str:
        """Log one completed request; returns the outcome
        (``appended`` | ``deduped`` | ``dropped``).  ``req`` is the
        service's ``_Request`` (duck-typed: ``concat``/``points``/
        ``bbox``/``shape_hw``/``digest``/``gen_id``/``session_id``/
        ``store_session``); ``prob`` is the crop-space probability map
        the dispatch just produced.  Never raises into the worker: any
        example it cannot derive is a counted drop."""
        if req.points is None or req.bbox is None:
            return self._drop("no_crop")
        warm = req.concat is None
        if warm:
            with self._lock:
                crop = self._crops.get(req.session_id)
            if crop is None:
                # the cold crop aged out of the LRU — a warm click
                # alone cannot reconstruct pixels
                return self._drop("no_crop")
        else:
            crop = np.ascontiguousarray(req.concat[..., :3], np.float32)
            if req.store_session and req.session_id:
                with self._lock:
                    self._crops[req.session_id] = crop
                    self._crops.move_to_end(req.session_id)
                    while len(self._crops) > _CROP_CACHE:
                        self._crops.popitem(last=False)
        # chaos seam: a ``nan`` fault here poisons the example exactly as
        # a corrupted client/annotation pipeline would — float leaves
        # (the crop) NaN-fill, the uint8 mask passes through — feeding
        # the poisoned_flywheel scenario's containment chain
        payload = chaos_sites.fire(
            "serve/session_append",
            payload={"crop": crop, "prob": np.asarray(prob)},
            session_id=req.session_id)
        crop, prob = payload["crop"], payload["prob"]
        mask = (np.asarray(prob) >= 0.5).astype(np.uint8)
        return self.append(
            crop=crop, mask=mask, points=np.asarray(req.points, np.float64),
            bbox=req.bbox, shape_hw=req.shape_hw, digest=int(req.digest),
            gen_id=int(req.gen_id or 0), warm=warm)

    def append(self, *, crop, mask, points, bbox, shape_hw, digest: int = 0,
               gen_id: int = 0, warm: bool = False) -> str:
        """The core append: dedup -> budget -> blob + index row.
        Returns the outcome string (see :meth:`offer`)."""
        crop = np.ascontiguousarray(crop, np.float32)
        mask = np.ascontiguousarray(mask, np.uint8)
        h, w = crop.shape[:2]
        if (h, w) != self.resolution:
            # geometry drift (a swap cannot change resolution by
            # construction, but a direct caller could): never log a
            # record replay couldn't feed the model
            return self._drop("no_crop")
        if digest == 0:
            # stateless request: no submit-thread digest — fingerprint
            # the crop bytes themselves (once, here; never per-click on
            # the session path)
            digest = zlib.crc32(crop.tobytes()) & 0xFFFFFFFF
            digest = digest or 1  # 0 is the "absent" sentinel
        key = dedup_key(digest, points)
        blob = encode_blob(crop, mask)
        with self._lock:
            if key in self._dedup:
                self._deduped += 1
                self._c_deduped.inc()
                return "deduped"
            if (self._n_records + 1 > self.max_records
                    or self._bin_bytes + len(blob) > self.max_bytes):
                self._dropped["budget"] += 1
                self._c_dropped["budget"].inc()
                return "dropped"
            row = np.zeros(1, SESSION_INDEX_DTYPE)[0]
            row["blob_offset"] = self._bin_bytes
            row["blob_len"] = len(blob)
            row["height"], row["width"] = h, w
            row["shape_h"], row["shape_w"] = int(shape_hw[0]), int(shape_hw[1])
            row["bbox"] = np.asarray(bbox, np.int64)
            row["points"] = np.asarray(points, np.float64)
            row["digest"] = digest
            row["dedup"] = key
            row["gen_id"] = gen_id
            row["warm"] = int(bool(warm))
            row["blob_crc32"] = zlib.crc32(blob) & 0xFFFFFFFF
            row_bytes = row.tobytes()
            self._bin.write(blob)
            self._idx.write(row_bytes)
            self._bin_bytes += len(blob)
            self._n_records += 1
            # incremental index crc: append-only, so the running crc of
            # the committed+pending prefix is exact
            self._index_crc = zlib.crc32(row_bytes, self._index_crc) \
                & 0xFFFFFFFF
            self._dedup.add(key)
            self._appended += 1
            self._c_appended.inc()
            self._dirty = True
            return "appended"

    def _drop(self, reason: str) -> str:
        with self._lock:
            self._dropped[reason] += 1
        self._c_dropped[reason].inc()
        return "dropped"

    # ------------------------------------------------------------ inspection
    def snapshot(self) -> dict:
        """The health()/bench view: committed log size + THIS sink's
        outcome tallies (instance-local, the ServeMetrics delta
        convention — the registry keeps process-lifetime totals)."""
        with self._lock:
            return {
                "path": self.path,
                "records": self._n_records,
                "bytes": self._bin_bytes,
                "appended": self._appended,
                "deduped": self._deduped,
                "dropped": dict(self._dropped),
                "max_bytes": self.max_bytes,
                "max_records": self.max_records,
            }

    def __str__(self) -> str:
        return (f"SessionLogSink({self.path},n={self._n_records},"
                f"bytes={self._bin_bytes})")
