"""Fleet front: multi-replica serving behind one consistent-hash router.

One ``dptpu-serve`` process is one failure domain: a wedged backend, a
hot-swap gone bad, or a SIGKILL is a full outage.  This module is the
ROADMAP's third serving leg — the multi-replica front the int8 path and
the AOT cache (near-instant replica boots) made worth building.  It is
deliberately composed from proven parts rather than new mechanism:

* **Routing** (serve/router.py): session-carrying requests route by
  consistent hash of ``session_id`` over the ring of live replicas —
  sessions are generation- and cache-affine (serve/sessions.py), so
  affinity is the router's job, exactly as the ROADMAP states.  A
  membership change moves only ~K/N sessions, and a moved session is
  not an error: its next click misses ``covers()`` on the new replica
  and degrades to ONE counted re-encode.  Stateless requests route
  least-loaded on the queue-depth/p99 signals every replica already
  exposes on ``/healthz``.
* **Membership**: a replica registry with a per-replica state machine
  ``starting -> healthy -> degraded -> draining -> dead``, driven by a
  background health loop polling ``/healthz`` under the chaos
  :class:`~..chaos.policies.Retry` / :class:`~..chaos.policies
  .CircuitBreaker` policies per replica.  Ring membership is
  health-driven: healthy+degraded replicas take traffic, draining and
  dead ones leave the ring (their key ranges rehash minimally).
* **Failover**: a request whose replica dies mid-flight (connection
  error before any HTTP reply) is retried ONCE on the next ring
  candidate and the reply carries ``X-Fleet-Rerouted: <dead-replica>``.
  A replica that answered — even with an error — is never retried: the
  429/504/503 shed taxonomy passes through byte-for-byte, and a reply
  already received may have had effects (session created, example
  logged) the front must not duplicate.
* **Supervision** (``local`` mode): the front spawns N ``dptpu-serve``
  children (ride ``--warmup --aot-cache`` for boots in seconds, not
  minutes), respawns dead ones under a restart budget, and — with
  ``--autoscale`` — actuates the scale plan with the governor's
  escalate/disarm hysteresis (data/governor.py's idiom).  ``attach``
  mode is the same front as a pure router over replicas given by URL.
* **Autoscale surface**: ``GET /fleet/plan`` returns the scale
  recommendation derived from aggregate queue depth and p99 vs target.
  Recommendation is deliberately separate from actuation: the plan is
  pure arithmetic any orchestrator (or a human) can read and apply,
  while actuation needs process ownership, hysteresis, and a restart
  budget — ``local --autoscale`` is one actuator, not the only one.

Observability: fleet gauges/counters (``fleet_replicas_live``,
``fleet_route_total{reason}``, ``fleet_failover_total``, per-replica
p99 gauges) in the process registry behind ``GET /metrics``, and fleet
events (``replica_up/down/drain``, ``failover``, ``scale_decision``)
into the flight recorder (telemetry/events.py) so ``dptpu-doctor`` can
stitch a replica-kill episode from the same timeline as everything
else.  Chaos seams: ``serve/route`` on the proxy path and
``serve/health_poll`` in the poll loop (the ``replica_kill_under_load``
scenario's wiring).

Stdlib-only (urllib + http.server + subprocess), importable pre-jax:
the front is a host process that must boot instantly and never touch a
device — all device work lives in the replicas.
"""

from __future__ import annotations

import http.client
import json
import os
import re
import shlex
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..chaos import policies
from ..chaos import sites as chaos_sites
from ..telemetry import events as events_lib
from ..telemetry.registry import get_registry
from .router import HashRing, least_loaded

#: the replica state machine, in lifecycle order
REPLICA_STATES = ("starting", "healthy", "degraded", "draining", "dead")

#: states whose replicas take traffic (ring + least-loaded membership):
#: degraded stays IN — its signals are bad but it answered, and evicting
#: it would rehash its sessions (a re-encode each) on every blip; only
#: confirmed-dead and deliberately-draining replicas leave the ring
LIVE_STATES = frozenset(("healthy", "degraded"))

#: consecutive failed polls (or mid-flight proxy failures) before a
#: replica is declared dead — also each replica's breaker threshold
DEAD_AFTER = 3


# --------------------------------------------------------------- autoscale

def scale_plan(loads: dict, n_live: int, *, target_p99_ms: float = 250.0,
               queue_high: float = 0.5, min_replicas: int = 1,
               max_replicas: int = 8) -> dict:
    """The scale recommendation — pure arithmetic over the last health
    polls, no actuation (``GET /fleet/plan``'s whole body).

    Pressure is the worse of two normalized signals: aggregate queue
    fraction vs ``queue_high`` (sustained above it, the bounded queues
    are absorbing a backlog the fleet can't drain) and mean p99 vs
    ``target_p99_ms``.  ``>= 1.0`` recommends scaling up proportionally
    (capped at doubling per decision — a thundering recommendation is
    how oscillation starts); ``<= 0.35`` with headroom recommends ONE
    replica down (scale-down is always stepwise: each removal rehashes
    sessions, so shed capacity slowly).  Between the two thresholds the
    recommendation is "hold" — the same dead band the governor's
    escalate/disarm hysteresis then widens in time."""
    depth = cap = 0
    p99s = []
    for sig in loads.values():
        if sig.get("queue_depth") is not None and sig.get("queue_capacity"):
            depth += int(sig["queue_depth"])
            cap += int(sig["queue_capacity"])
        if sig.get("p99_ms") is not None:
            p99s.append(float(sig["p99_ms"]))
    qfrac = (depth / cap) if cap else None
    p99 = (sum(p99s) / len(p99s)) if p99s else None
    pressures = {}
    if qfrac is not None:
        pressures["queue"] = qfrac / queue_high
    if p99 is not None:
        pressures["p99"] = p99 / target_p99_ms
    pressure = max(pressures.values()) if pressures else None
    if n_live < 1 or pressure is None:
        recommended = max(n_live, min_replicas)
        reason = ("no live replicas" if n_live < 1
                  else "no load signals yet; hold")
    elif pressure >= 1.0:
        import math

        recommended = min(max_replicas,
                          max(n_live + 1,
                              math.ceil(n_live * min(pressure, 2.0))))
        reason = (f"pressure {pressure:.2f} >= 1.0 "
                  f"({'queue' if pressures.get('queue') == pressure else 'p99'}"
                  " bound)")
    elif pressure <= 0.35 and n_live > min_replicas:
        recommended = n_live - 1
        reason = f"pressure {pressure:.2f} <= 0.35; shed one replica"
    else:
        recommended = n_live
        reason = f"pressure {pressure:.2f} in the hold band"
    return {
        "replicas_live": n_live,
        "recommended": recommended,
        "delta": recommended - n_live,
        "pressure": None if pressure is None else round(pressure, 4),
        "queue_fraction": None if qfrac is None else round(qfrac, 4),
        "p99_ms": None if p99 is None else round(p99, 3),
        "targets": {"p99_ms": target_p99_ms, "queue_high": queue_high,
                    "min_replicas": min_replicas,
                    "max_replicas": max_replicas},
        "reason": reason,
    }


class AutoscaleGovernor:
    """Escalate/disarm hysteresis between the plan and the actuator —
    the data/governor.py idiom applied to replica count: a recommendation
    must HOLD for ``escalate_patience`` consecutive ticks before scaling
    up (one slow batch must not spawn a replica) and for
    ``disarm_patience`` ticks before scaling down (scale-down rehashes
    sessions, so be much slower to shrink than to grow).  Any tick in
    the hold band zeroes both counters.  Single-threaded by design: only
    the health-poll loop ticks it."""

    def __init__(self, escalate_patience: int = 3,
                 disarm_patience: int = 10):
        self.escalate_patience = int(escalate_patience)
        self.disarm_patience = int(disarm_patience)
        self._up_ticks = 0
        self._down_ticks = 0
        #: decisions taken, newest last (the ops surface)
        self.decisions: list[dict] = []

    def tick(self, plan: dict) -> dict | None:
        """One poll-cadence tick; returns an actionable decision
        ``{"action": "scale_up"|"scale_down", "to": n, "plan": ...}``
        or None (holding / still counting)."""
        if plan["delta"] > 0:
            self._up_ticks += 1
            self._down_ticks = 0
            if self._up_ticks >= self.escalate_patience:
                self._up_ticks = 0
                decision = {"action": "scale_up",
                            "to": plan["recommended"], "plan": plan}
                self.decisions.append(decision)
                return decision
        elif plan["delta"] < 0:
            self._down_ticks += 1
            self._up_ticks = 0
            if self._down_ticks >= self.disarm_patience:
                self._down_ticks = 0
                decision = {"action": "scale_down",
                            "to": plan["recommended"], "plan": plan}
                self.decisions.append(decision)
                return decision
        else:
            self._up_ticks = 0
            self._down_ticks = 0
        return None

    def snapshot(self) -> dict:
        return {"up_ticks": self._up_ticks, "down_ticks": self._down_ticks,
                "escalate_patience": self.escalate_patience,
                "disarm_patience": self.disarm_patience,
                "decisions": len(self.decisions)}


# --------------------------------------------------------------- registry

class FleetRegistry:
    """Replica membership + state machine + the hash ring, under ONE
    lock.  All mutation goes through methods that (a) hold the lock only
    for pure bookkeeping — never network, file, or process I/O — and
    (b) return the fleet events the transition produced, which the
    CALLER emits after the lock is released (the flight recorder's
    writer takes its own lock; nesting it under ours would order-couple
    two unrelated locks for no benefit)."""

    def __init__(self, vnodes: int | None = None):
        self._lock = threading.Lock()
        self._urls: dict[str, str] = {}          # jaxrace: guarded-by=self._lock
        self._states: dict[str, str] = {}        # jaxrace: guarded-by=self._lock
        self._since: dict[str, float] = {}       # jaxrace: guarded-by=self._lock
        self._signals: dict[str, dict] = {}      # jaxrace: guarded-by=self._lock
        self._failures: dict[str, int] = {}      # jaxrace: guarded-by=self._lock
        ring = HashRing() if vnodes is None else HashRing(vnodes=vnodes)
        self._ring = ring                    # jaxrace: guarded-by=self._lock
        self._vnodes = self._ring.vnodes
        self._gauge_live = get_registry().gauge(
            "fleet_replicas_live", "replicas currently taking traffic")

    # -- membership ------------------------------------------------------

    def add(self, rid: str, url: str) -> list[dict]:
        """Register ``rid`` at ``url`` in state ``starting``; idempotent
        re-add of a known id re-points its url (a respawned local
        replica keeps its id — and therefore its ring ranges — so its
        sessions come home after one re-encode)."""
        with self._lock:
            fresh = rid not in self._states
            self._urls[rid] = url
            self._states[rid] = "starting"
            self._since[rid] = time.monotonic()
            self._signals.setdefault(rid, {})
            self._failures[rid] = 0
            self._ring.remove(rid)  # starting replicas take no traffic
            self._update_live_gauge()
        return [{"kind": "replica_starting" if fresh else "replica_respawn",
                 "payload": {"replica": rid, "url": url}}]

    def remove(self, rid: str) -> list[dict]:
        """Deregister ``rid`` entirely (its ring ranges rehash)."""
        with self._lock:
            if rid not in self._states:
                return []
            state = self._states.pop(rid)
            self._urls.pop(rid, None)
            self._since.pop(rid, None)
            self._signals.pop(rid, None)
            self._failures.pop(rid, None)
            self._ring.remove(rid)
            self._update_live_gauge()
        return [{"kind": "replica_removed",
                 "payload": {"replica": rid, "from_state": state}}]

    def drain(self, rid: str) -> list[dict]:
        """Take ``rid`` out of the ring without killing it: in-flight
        work completes, its sessions rehash (a re-encode each) to the
        survivors, and the operator (or the autoscaler) removes it once
        its queue runs dry."""
        return self._transition(rid, "draining", "drain requested")

    # -- health-driven transitions --------------------------------------

    def note_poll(self, rid: str, ok: bool, signals: dict | None = None,
                  reason: str = "", boot_timeout_s: float = 300.0
                  ) -> list[dict]:
        """Apply one health-poll outcome.  ``ok`` means the replica
        answered /healthz AND reported itself healthy; an answered-but-
        unhealthy poll passes ``ok=False`` with its reason.  Repeated
        failures (``DEAD_AFTER``) kill the replica — except while
        ``starting``, where connection refusals are just a boot in
        progress until ``boot_timeout_s`` runs out."""
        with self._lock:
            if rid not in self._states:
                return []
            state = self._states[rid]
            if signals is not None:
                self._signals[rid] = dict(signals)
            if ok:
                self._failures[rid] = 0
                if state in ("starting", "degraded"):
                    return self._set_state_locked(rid, "healthy", reason)
                return []
            self._failures[rid] += 1
            if state == "starting":
                booting = (time.monotonic() - self._since[rid]
                           < boot_timeout_s)
                if booting:
                    return []
                return self._set_state_locked(
                    rid, "dead", f"boot timeout: {reason}")
            if state == "draining":
                return []  # a draining replica winding down is not news
            if self._failures[rid] >= DEAD_AFTER:
                return self._set_state_locked(
                    rid, "dead",
                    f"{self._failures[rid]} consecutive failures: {reason}")
            if state == "healthy":
                return self._set_state_locked(rid, "degraded", reason)
        return []

    def note_proxy_failure(self, rid: str, reason: str) -> list[dict]:
        """A request to ``rid`` failed at the CONNECTION level mid-flight
        — stronger evidence than a missed poll (a real client just got
        hurt), so it counts like a failed poll immediately instead of
        waiting out the poll interval."""
        return self.note_poll(rid, ok=False, reason=f"proxy: {reason}",
                              boot_timeout_s=0.0)

    def _transition(self, rid: str, state: str, reason: str) -> list[dict]:
        with self._lock:
            if rid not in self._states:
                return []
            return self._set_state_locked(rid, state, reason)

    def _set_state_locked(self, rid: str, state: str,
                          reason: str) -> list[dict]:
        """State write + ring membership + gauge, caller holds the lock.
        Returns the fleet events to emit (outside the lock)."""
        prev = self._states[rid]
        if prev == state:
            return []
        self._states[rid] = state
        self._since[rid] = time.monotonic()
        if state in LIVE_STATES:
            self._ring.add(rid)
        else:
            self._ring.remove(rid)
        self._update_live_gauge()
        kind = {"healthy": "replica_up", "dead": "replica_down",
                "draining": "replica_drain"}.get(state, "replica_state")
        return [{"kind": kind,
                 "payload": {"replica": rid, "from": prev, "to": state,
                             "reason": reason}}]

    def _update_live_gauge(self) -> None:
        self._gauge_live.set(
            sum(1 for s in self._states.values() if s in LIVE_STATES))

    # -- read surface ----------------------------------------------------

    def ids(self) -> list[str]:
        with self._lock:
            return sorted(self._states)

    def url(self, rid: str) -> str | None:
        with self._lock:
            return self._urls.get(rid)

    def state(self, rid: str) -> str | None:
        with self._lock:
            return self._states.get(rid)

    def candidates(self, session_id: str) -> list[str]:
        """Failover-ordered live replicas for a session key."""
        with self._lock:
            return self._ring.candidates(session_id)

    def live_loads(self) -> dict[str, dict]:
        """id -> last load signals, live replicas only (the least-loaded
        router's and the autoscaler's shared input)."""
        with self._lock:
            return {rid: dict(self._signals.get(rid) or {})
                    for rid, s in self._states.items() if s in LIVE_STATES}

    def n_live(self) -> int:
        with self._lock:
            return sum(1 for s in self._states.values() if s in LIVE_STATES)

    def snapshot(self) -> dict:
        """The /healthz replica table."""
        with self._lock:
            now = time.monotonic()
            return {
                "replicas": {
                    rid: {"url": self._urls.get(rid),
                          "state": s,
                          "state_age_s": round(now - self._since[rid], 3),
                          "consecutive_failures": self._failures.get(rid, 0),
                          "signals": dict(self._signals.get(rid) or {})}
                    for rid, s in sorted(self._states.items())},
                "ring": sorted(self._ring.nodes),
                "vnodes": self._vnodes,
            }


# ---------------------------------------------------------- local manager

class LocalManager:
    """Spawn/respawn ``dptpu-serve`` children for ``local`` mode.

    ``argv_template`` is the replica command WITHOUT host/port (the
    manager appends ``--host 127.0.0.1 --port <free port>``);
    ``child_env(slot, restarts)`` may return extra env for one spawn
    (the chaos runner injects a fault plan into exactly one replica's
    FIRST boot this way).  Slot ids are stable (``r0..rN-1``): a
    respawn reuses its slot's id, so the ring's key ranges — and
    therefore session affinity — survive the restart."""

    def __init__(self, argv_template: list[str], workdir: str,
                 max_restarts: int = 3, child_env=None):
        self.argv_template = list(argv_template)
        self.workdir = workdir
        self.max_restarts = int(max_restarts)
        self.child_env = child_env
        self._lock = threading.Lock()
        self._procs: dict[str, subprocess.Popen] = {}  # jaxrace: guarded-by=self._lock
        self._restarts: dict[str, int] = {}            # jaxrace: guarded-by=self._lock
        self._next_slot = 0                            # jaxrace: guarded-by=self._lock
        os.makedirs(workdir, exist_ok=True)

    @staticmethod
    def _free_port() -> int:
        s = socket.socket()
        try:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]
        finally:
            s.close()

    def new_slot(self) -> str:
        with self._lock:
            rid = f"r{self._next_slot}"
            self._next_slot += 1
            self._restarts.setdefault(rid, 0)
        return rid

    def spawn(self, rid: str) -> str:
        """Launch one child for slot ``rid``; returns its URL.  All the
        process I/O happens before the (brief) bookkeeping lock."""
        port = self._free_port()
        argv = self.argv_template + ["--host", "127.0.0.1",
                                     "--port", str(port)]
        with self._lock:
            restarts = self._restarts.get(rid, 0)
        env = dict(os.environ)
        extra = self.child_env(rid, restarts) if self.child_env else None
        if extra:
            env.update(extra)
        log = open(os.path.join(self.workdir, f"{rid}.log"), "ab")
        try:
            proc = subprocess.Popen(argv, env=env, stdout=log, stderr=log)
        finally:
            log.close()  # the child holds its own fd
        with self._lock:
            self._procs[rid] = proc
        return f"http://127.0.0.1:{port}"

    def kill(self, rid: str, sig=None) -> None:
        """Terminate slot ``rid``'s child (SIGTERM default)."""
        with self._lock:
            proc = self._procs.get(rid)
        if proc is None or proc.poll() is not None:
            return
        if sig is None:
            proc.terminate()
        else:
            proc.send_signal(sig)

    def pid(self, rid: str) -> int | None:
        with self._lock:
            proc = self._procs.get(rid)
        return None if proc is None or proc.poll() is not None else proc.pid

    def exited(self, rid: str) -> bool:
        with self._lock:
            proc = self._procs.get(rid)
        return proc is not None and proc.poll() is not None

    def can_respawn(self, rid: str) -> bool:
        with self._lock:
            return self._restarts.get(rid, 0) < self.max_restarts

    def respawn(self, rid: str) -> str | None:
        """Respawn a dead slot under the restart budget; returns the new
        URL or None (budget spent)."""
        with self._lock:
            if self._restarts.get(rid, 0) >= self.max_restarts:
                return None
            self._restarts[rid] = self._restarts.get(rid, 0) + 1
        return self.spawn(rid)

    def retire(self, rid: str) -> None:
        """Drop a slot for good (scale-down): SIGTERM + no respawn."""
        self.kill(rid)
        with self._lock:
            self._restarts[rid] = self.max_restarts

    def stop_all(self, timeout_s: float = 10.0) -> None:
        with self._lock:
            procs = dict(self._procs)
        for proc in procs.values():
            if proc.poll() is None:
                proc.terminate()
        deadline = time.monotonic() + timeout_s
        for proc in procs.values():
            remaining = deadline - time.monotonic()
            try:
                proc.wait(timeout=max(0.1, remaining))
            except subprocess.TimeoutExpired:
                proc.kill()


# ------------------------------------------------------------- conn pool

class _ReplicaPool:
    """Keep-alive ``http.client`` connections to replicas, shared
    across the front's handler threads.

    ThreadingHTTPServer spawns a thread per CLIENT connection, so
    thread-local reuse would never hit — the pool is one free-list per
    replica URL under a lock that guards bookkeeping only: connects,
    closes and all request I/O happen outside it (jaxrace JR004).
    Reuse is what keeps the hop cheap enough for the bench's
    proxy-overhead pin: a fresh TCP connect plus a fresh replica-side
    handler thread per forwarded request costs more than the routing
    itself."""

    #: idle connections kept per replica; surplus returns just close
    MAX_IDLE = 8

    def __init__(self, timeout_s: float):
        self.timeout_s = float(timeout_s)
        self._lock = threading.Lock()
        self._idle: dict[str, list] = {}  # jaxrace: guarded-by=self._lock

    def fresh(self, url: str) -> http.client.HTTPConnection:
        host, port = url.split("//", 1)[1].rsplit(":", 1)
        conn = http.client.HTTPConnection(host, int(port),
                                          timeout=self.timeout_s)
        conn.connect()
        try:
            conn.sock.setsockopt(socket.IPPROTO_TCP,
                                 socket.TCP_NODELAY, 1)
        except OSError:
            pass  # best-effort: Nagle costs only latency, never bytes
        return conn

    def take(self, url: str) -> tuple[http.client.HTTPConnection, bool]:
        """An idle pooled connection or a fresh one; the bool says
        which (a STALE pooled connection failing is a keep-alive
        artifact worth one same-replica retry — a fresh one failing is
        transport evidence)."""
        with self._lock:
            conns = self._idle.get(url)
            conn = conns.pop() if conns else None
        if conn is not None:
            return conn, False
        return self.fresh(url), True

    def give(self, url: str, conn) -> None:
        surplus = None
        with self._lock:
            conns = self._idle.setdefault(url, [])
            if len(conns) < self.MAX_IDLE:
                conns.append(conn)
            else:
                surplus = conn
        if surplus is not None:
            surplus.close()

    def drop(self, url: str) -> None:
        """Close every idle connection to ``url`` — its replica just
        failed a forward, so the rest of its pool is as stale."""
        with self._lock:
            conns = self._idle.pop(url, [])
        for c in conns:
            c.close()

    def close_all(self) -> None:
        with self._lock:
            conns = [c for cs in self._idle.values() for c in cs]
            self._idle.clear()
        for c in conns:
            c.close()


# ------------------------------------------------------------------ front

class FleetFront:
    """The fleet: registry + health loop + HTTP router (+ supervisor in
    ``local`` mode).

    >>> front = FleetFront(attach=["http://127.0.0.1:8801"])
    >>> front.start()
    >>> url = front.serve_http("127.0.0.1", 0)   # background server
    >>> ...
    >>> front.stop()
    """

    def __init__(self, attach: list[str] | None = None,
                 manager: LocalManager | None = None,
                 replicas: int = 0,
                 poll_interval_s: float = 1.0,
                 poll_timeout_s: float = 5.0,
                 boot_timeout_s: float = 300.0,
                 proxy_timeout_s: float = 120.0,
                 target_p99_ms: float = 250.0,
                 min_replicas: int = 1, max_replicas: int = 8,
                 autoscale: bool = False,
                 governor: AutoscaleGovernor | None = None,
                 vnodes: int | None = None):
        if attach and manager is not None:
            raise ValueError("attach URLs and a LocalManager are exclusive "
                             "modes — pass one")
        if manager is not None and replicas < 1:
            raise ValueError(f"local mode needs replicas >= 1, "
                             f"got {replicas}")
        self.registry = FleetRegistry(vnodes=vnodes)
        self.manager = manager
        self.mode = "local" if manager is not None else "attach"
        self._n_start = replicas
        self._attach = list(attach or [])
        self.poll_interval_s = float(poll_interval_s)
        self.poll_timeout_s = float(poll_timeout_s)
        self.boot_timeout_s = float(boot_timeout_s)
        self.proxy_timeout_s = float(proxy_timeout_s)
        self._pool = _ReplicaPool(self.proxy_timeout_s)
        self.target_p99_ms = float(target_p99_ms)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.autoscale = bool(autoscale) and self.mode == "local"
        self.governor = governor or AutoscaleGovernor()
        #: per-replica poll breakers: a dead replica is refused, not
        #: hammered; half-open after 2 poll intervals re-probes it
        self._breakers: dict[str, policies.CircuitBreaker] = {}
        #: in-poll retry: one quick second chance absorbs a blip without
        #: waiting a full interval to clear a degraded flap
        self._poll_retry = policies.Retry(base_s=0.05, cap_s=0.2,
                                          attempts=2, jitter=0.0)
        self._autodrain: set[str] = set()   # jaxrace: guarded-by=self._drain_lock
        self._drain_empty: dict[str, int] = {}  # jaxrace: guarded-by=self._drain_lock
        self._drain_lock = threading.Lock()
        reg = get_registry()
        self._route_total = {
            reason: reg.counter("fleet_route_total",
                                "requests routed, by routing reason",
                                labels={"reason": reason})
            for reason in ("session", "stateless", "unroutable")}
        self._failover_total = reg.counter(
            "fleet_failover_total",
            "requests retried on the next ring candidate after their "
            "replica died mid-flight")
        self._p99_gauges: dict[str, object] = {}
        self._stop = threading.Event()
        self._poller: threading.Thread | None = None
        self._httpd: ThreadingHTTPServer | None = None
        self._http_thread: threading.Thread | None = None
        self._started = False

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "FleetFront":
        if self._started:
            raise RuntimeError("fleet already started")
        self._started = True
        chaos_sites.maybe_arm_from_env()
        if self.mode == "local":
            for _ in range(self._n_start):
                rid = self.manager.new_slot()
                url = self.manager.spawn(rid)
                self._emit(self.registry.add(rid, url))
        else:
            for i, url in enumerate(self._attach):
                self._emit(self.registry.add(f"a{i}", url.rstrip("/")))
        self._poller = threading.Thread(target=self._poll_loop,
                                        name="fleet-health", daemon=True)
        self._poller.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._pool.close_all()
        if self._poller is not None:
            self._poller.join(timeout=10.0)
            self._poller = None
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self.manager is not None:
            self.manager.stop_all()

    def __enter__(self) -> "FleetFront":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop()

    def serve_http(self, host: str = "127.0.0.1", port: int = 0) -> str:
        """Start the HTTP front on a background thread; returns its URL.
        (The CLI instead runs :meth:`serve_forever` on the main
        thread.)"""
        self._httpd = ThreadingHTTPServer((host, port),
                                          make_fleet_handler(self))
        self._http_thread = threading.Thread(
            target=lambda: self._httpd.serve_forever(poll_interval=0.05),
            name="fleet-http", daemon=True)
        self._http_thread.start()
        return f"http://{host}:{self._httpd.server_address[1]}"

    def wait_live(self, n: int, timeout_s: float = 300.0) -> bool:
        """Block until ``n`` replicas are live (or timeout); the boot
        barrier for tests, benches, and the CLI's ready line."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.registry.n_live() >= n:
                return True
            if self._stop.wait(0.05):
                return False
        return self.registry.n_live() >= n

    # -- events / metrics ------------------------------------------------

    def _emit(self, evs: list[dict]) -> None:
        for ev in evs:
            events_lib.emit("fleet", ev["kind"], payload=ev["payload"])

    def _observe_p99(self, rid: str, signals: dict) -> None:
        p99 = signals.get("p99_ms")
        if p99 is None:
            return
        g = self._p99_gauges.get(rid)
        if g is None:
            g = self._p99_gauges[rid] = get_registry().gauge(
                "fleet_replica_p99_ms",
                "per-replica request p99 from the last health poll",
                labels={"replica": rid})
        g.set(float(p99))

    # -- health loop -----------------------------------------------------

    def _poll_loop(self) -> None:
        while not self._stop.is_set():
            self._tick()
            self._stop.wait(self.poll_interval_s)

    def _tick(self) -> None:
        """One health round: poll every replica, apply transitions,
        respawn dead local slots, drive the drain/autoscale machinery."""
        for rid in self.registry.ids():
            url = self.registry.url(rid)
            if url is None:
                continue
            ok, signals, reason = self._poll_one(rid, url)
            if signals:
                self._observe_p99(rid, signals)
            self._emit(self.registry.note_poll(
                rid, ok, signals=signals, reason=reason,
                boot_timeout_s=self.boot_timeout_s))
        if self.mode == "local":
            self._reap_and_respawn()
            self._finish_drains()
        plan = self.plan()
        if self.autoscale:
            decision = self.governor.tick(plan)
            if decision is not None:
                self._actuate(decision)

    def _poll_one(self, rid: str, url: str
                  ) -> tuple[bool, dict | None, str]:
        """GET /healthz under the per-replica breaker + in-poll retry.
        Returns (ok, load signals, reason)."""
        breaker = self._breakers.get(rid)
        if breaker is None:
            breaker = self._breakers[rid] = policies.CircuitBreaker(
                failure_threshold=DEAD_AFTER,
                reset_after_s=2.0 * self.poll_interval_s)

        def fetch() -> dict:
            # chaos seam: a latency fault is a slow replica (poll still
            # truthful), an error fault is a poll that never lands —
            # counted toward the replica's failure tally like any
            # network failure (the membership chaos the scenario drives)
            chaos_sites.fire("serve/health_poll", replica=rid)
            with urllib.request.urlopen(url + "/healthz",
                                        timeout=self.poll_timeout_s) as r:
                return json.loads(r.read().decode("utf-8"))

        def fetch_allowing_unhealthy() -> dict:
            # a 503 /healthz carries the SAME body (the probe's answer,
            # not an error): a replica honest about being unhealthy has
            # answered — only transport failures count against the
            # breaker
            try:
                return fetch()
            except urllib.error.HTTPError as e:
                return json.loads(e.read().decode("utf-8"))

        try:
            health = self._poll_retry.call(
                lambda: breaker.call(fetch_allowing_unhealthy),
                retry_on=(urllib.error.URLError, OSError, ValueError))
        except policies.CircuitOpenError:
            return False, None, "breaker open"
        except policies.RetryBudgetExceededError as e:
            cause = e.__cause__
            return False, None, (f"{type(cause).__name__}: {cause}"
                                 if cause else "poll failed")
        except Exception as e:  # noqa: BLE001 — a poll must never kill the loop
            return False, None, f"{type(e).__name__}: {e}"
        stats = health.get("stats") or {}
        lat = stats.get("latency_ms") or {}
        signals = {
            "queue_depth": health.get("queue_depth"),
            "queue_capacity": health.get("queue_capacity"),
            "p99_ms": lat.get("p99"),
            "p50_ms": lat.get("p50"),
            "completed": stats.get("completed"),
            "unhealthy_reason": health.get("unhealthy_reason"),
        }
        ok = bool(health.get("ok"))
        return ok, signals, (signals["unhealthy_reason"] or "")

    def _reap_and_respawn(self) -> None:
        """Local mode: a slot whose process exited is dead NOW (no need
        to wait out DEAD_AFTER polls), and dead slots respawn under the
        restart budget — scale-out in seconds when the replicas boot
        off the AOT cache."""
        for rid in self.registry.ids():
            state = self.registry.state(rid)
            if state != "dead" and self.manager.exited(rid):
                self._emit(self.registry.note_poll(
                    rid, ok=False, reason="process exited",
                    boot_timeout_s=0.0))
                self._emit(self.registry.note_poll(
                    rid, ok=False, reason="process exited",
                    boot_timeout_s=0.0))
                self._emit(self.registry.note_poll(
                    rid, ok=False, reason="process exited",
                    boot_timeout_s=0.0))
                state = self.registry.state(rid)
            if state == "dead" and self.manager.can_respawn(rid):
                with self._drain_lock:
                    draining = rid in self._autodrain
                if draining:
                    continue  # scale-down took it; let it die
                url = self.manager.respawn(rid)
                if url is not None:
                    self._emit(self.registry.add(rid, url))

    def _finish_drains(self) -> None:
        """A scale-down drain completes when the replica's queue reads
        empty for two consecutive polls — then the child is retired and
        the slot deregistered."""
        with self._drain_lock:
            draining = list(self._autodrain)
        for rid in draining:
            sig = (self.registry.live_loads().get(rid)
                   or self.registry.snapshot()["replicas"]
                   .get(rid, {}).get("signals") or {})
            empty = (sig.get("queue_depth") == 0)
            with self._drain_lock:
                n = self._drain_empty.get(rid, 0) + 1 if empty else 0
                self._drain_empty[rid] = n
                done = n >= 2
                if done:
                    self._autodrain.discard(rid)
                    self._drain_empty.pop(rid, None)
            if done:
                self.manager.retire(rid)
                self._emit(self.registry.remove(rid))

    def _actuate(self, decision: dict) -> None:
        """Apply a governor decision (local mode only): scale-up spawns
        fresh slots; scale-down DRAINS the newest slot (sessions rehash,
        queue empties, then the child retires) — never a kill."""
        events_lib.emit("fleet", "scale_decision", payload=decision)
        n_live = self.registry.n_live()
        if decision["action"] == "scale_up":
            for _ in range(max(0, decision["to"] - n_live)):
                rid = self.manager.new_slot()
                url = self.manager.spawn(rid)
                self._emit(self.registry.add(rid, url))
        elif decision["action"] == "scale_down" and n_live > decision["to"]:
            live = [rid for rid in self.registry.ids()
                    if self.registry.state(rid) in LIVE_STATES]
            if live:
                victim = live[-1]  # newest slot: fewest resident sessions
                with self._drain_lock:
                    self._autodrain.add(victim)
                    self._drain_empty[victim] = 0
                self._emit(self.registry.drain(victim))

    # -- routing ---------------------------------------------------------

    def route_order(self, session_id: str | None) -> tuple[list[str], str]:
        """The ordered replica candidates for one request and the
        routing reason.  Session requests: ring order (affinity, then
        failover); stateless: least-loaded order."""
        if session_id is not None:
            return self.registry.candidates(str(session_id)), "session"
        return least_loaded(self.registry.live_loads()), "stateless"

    def plan(self) -> dict:
        """``GET /fleet/plan``'s body — recommendation only, see
        :func:`scale_plan` for why actuation lives elsewhere."""
        return scale_plan(self.registry.live_loads(),
                          self.registry.n_live(),
                          target_p99_ms=self.target_p99_ms,
                          min_replicas=self.min_replicas,
                          max_replicas=self.max_replicas)

    def health(self) -> dict:
        reg = self.registry.snapshot()
        n_live = sum(1 for r in reg["replicas"].values()
                     if r["state"] in LIVE_STATES)
        return {
            "ok": n_live > 0,
            "mode": self.mode,
            "live": n_live,
            "autoscale": (self.governor.snapshot()
                          if self.autoscale else None),
            "events": events_lib.events_block(),
            **reg,
        }

    def count_route(self, reason: str) -> None:
        c = self._route_total.get(reason)
        if c is not None:
            c.inc()

    def count_failover(self, dead_rid: str, to_rid: str) -> None:
        self._failover_total.inc()
        events_lib.emit("fleet", "failover",
                        payload={"replica": dead_rid, "to": to_rid})


# ---------------------------------------------------------------- handler

#: routing scan: the quoted key, then ONE JSON scalar token — a string
#: (escapes included) or a bare literal/number.  Structured values do
#: not match and fall back to stateless routing.
_SESSION_TOKEN = re.compile(
    rb'"session_id"\s*:\s*("(?:[^"\\]|\\.)*"|[^,}\]\s]+)')


def make_fleet_handler(front: FleetFront) -> type:
    """The fleet's request-handler class, closed over the front.

    The proxy forwards the RAW request body (one ``json.loads`` for the
    routing fields only — arrays are never decoded or re-encoded on the
    hop) and passes replica replies through byte-for-byte, so the whole
    shed taxonomy (429 ``queue_full``/``session_lane``, 504, 503) and
    the client's typed round-trip survive the extra hop unchanged."""

    class FleetHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # same Nagle/delayed-ACK interaction as the replica handler:
        # header + body are two writes, keep-alive keeps the socket
        disable_nagle_algorithm = True
        # one segment per reply (see the replica handler's wbufsize)
        wbufsize = 64 * 1024
        timeout = 10.0

        def log_message(self, fmt, *args):  # metrics are the log
            pass

        def _reply(self, code: int, payload: dict,
                   headers: dict | None = None) -> None:
            body = json.dumps(payload).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if code in (429, 503) and not (headers or {}).get("Retry-After"):
                self.send_header("Retry-After", "1")
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self) -> None:  # noqa: N802 — http.server's contract
            if self.path == "/healthz":
                health = front.health()
                self._reply(200 if health["ok"] else 503, health)
            elif self.path == "/fleet/plan":
                self._reply(200, front.plan())
            elif self.path == "/metrics":
                from ..telemetry import prometheus

                text = prometheus.render_text(get_registry())
                body = text.encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", prometheus.CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._reply(404, {"error": f"no such path {self.path!r}"})

        def do_POST(self) -> None:  # noqa: N802
            try:
                raw = self.rfile.read(
                    int(self.headers.get("Content-Length", 0)))
            except (TimeoutError, OSError):
                self.close_connection = True
                return
            if self.path == "/v1/predict":
                self._proxy_predict(raw)
            elif self.path == "/fleet/drain":
                self._admin(raw, "drain")
            elif self.path == "/fleet/remove":
                self._admin(raw, "remove")
            elif self.path == "/fleet/add":
                self._admin(raw, "add")
            else:
                self._reply(404, {"error": f"no such path {self.path!r}"})

        # -- admin -------------------------------------------------------

        def _admin(self, raw: bytes, op: str) -> None:
            try:
                body = json.loads(raw.decode("utf-8")) if raw else {}
            except ValueError as e:
                self._reply(400, {"error": f"bad request: {e}"})
                return
            if op == "add":
                url = body.get("url")
                if front.mode != "attach":
                    self._reply(409, {"error": "add-by-url is an attach-"
                                               "mode operation; local "
                                               "replicas are supervised"})
                    return
                if not url:
                    self._reply(400, {"error": "need {'url': ...}"})
                    return
                rid = f"a{len(front.registry.ids())}"
                front._emit(front.registry.add(rid, str(url).rstrip("/")))
                self._reply(200, {"added": rid})
                return
            rid = body.get("replica")
            if rid is None or front.registry.state(rid) is None:
                self._reply(404, {"error": f"no replica {rid!r}"})
                return
            if op == "drain":
                front._emit(front.registry.drain(rid))
            else:
                if front.manager is not None:
                    front.manager.retire(rid)
                front._emit(front.registry.remove(rid))
            self._reply(200, front.health())

        # -- the proxy ---------------------------------------------------

        def _routing_fields(self, raw: bytes) -> str | None:
            """session_id from the request body, or None — the ONLY
            parse the hop does, and it is a token SCAN, not a full
            ``json.loads``: the body is dominated by the base64 image
            (whose alphabet cannot contain ``"``, so the quoted key
            cannot appear inside it) and decoding all of it just to
            route costs more than the rest of the hop combined.  A
            malformed body still routes (to any live replica): the
            replica's 400 is the authoritative answer and must come
            from the same validation path as a direct request's."""
            m = _SESSION_TOKEN.search(raw)
            if m is None:
                return None
            try:
                sid = json.loads(m.group(1).decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                return None
            return None if sid is None else str(sid)

        def _proxy_predict(self, raw: bytes) -> None:
            session_id = self._routing_fields(raw)
            try:
                chaos_sites.fire("serve/route", session=session_id)
            except Exception as e:  # noqa: BLE001 — injected route fault
                front.count_route("unroutable")
                self._reply(503, {"error": f"routing failed: {e}",
                                  "code": "fleet_unavailable"})
                return
            order, reason = front.route_order(session_id)
            if not order:
                front.count_route("unroutable")
                self._reply(503, {
                    "error": "no live replicas (all starting, draining "
                             "or dead) — retry shortly",
                    "code": "fleet_unavailable"})
                return
            rerouted_from: str | None = None
            # primary + ONE failover candidate: a request is retried at
            # most once, and only when its replica died before sending
            # any reply (a received error reply is final — see module
            # docstring on non-idempotent safety)
            for rid in order[:2]:
                url = front.registry.url(rid)
                if url is None:
                    continue
                try:
                    status, ctype, body, retry_after = self._forward(
                        url, raw)
                except (urllib.error.URLError, OSError,
                        http.client.HTTPException) as e:
                    reason_s = getattr(e, "reason", None) or e
                    front._pool.drop(url)
                    front._emit(front.registry.note_proxy_failure(
                        rid, str(reason_s)))
                    rerouted_from = rid
                    continue
                headers = {"X-Fleet-Replica": rid}
                if rerouted_from is not None:
                    headers["X-Fleet-Rerouted"] = rerouted_from
                    front.count_failover(rerouted_from, rid)
                if retry_after:
                    headers["Retry-After"] = retry_after
                elif status == 503 and front.registry.state(rid) in (
                        "draining", "starting"):
                    # a draining/booting replica's refusal is transient
                    # by definition: tell the client when to come back
                    headers["Retry-After"] = "1"
                front.count_route(reason)
                self._reply_raw(status, ctype, body, headers)
                return
            front.count_route("unroutable")
            headers = {}
            if rerouted_from is not None:
                headers["X-Fleet-Rerouted"] = rerouted_from
            self._reply(503, {
                "error": "replica died mid-flight and the failover "
                         "candidate was not reachable — retry shortly",
                "code": "fleet_unavailable"}, headers)

        def _forward(self, url: str, raw: bytes
                     ) -> tuple[int, str, bytes, str | None]:
            """One proxy attempt over a pooled keep-alive connection.
            An HTTP error REPLY (the replica answered) returns like a
            success — it is a pass-through payload, not a failover
            trigger; only transport-level failures raise.  A POOLED
            connection failing before any reply gets one retry on a
            fresh connection to the SAME replica: a dropped keep-alive
            is a connection artifact, not evidence against the replica
            — treating it as death would degrade healthy members and
            bounce their sessions."""
            conn, fresh = front._pool.take(url)
            while True:
                try:
                    conn.request("POST", "/v1/predict", body=raw,
                                 headers={"Content-Type":
                                          "application/json"})
                    resp = conn.getresponse()
                    body = resp.read()
                except (OSError, http.client.HTTPException):
                    conn.close()
                    if fresh:
                        raise
                    conn, fresh = front._pool.fresh(url), True
                    continue
                if resp.will_close:
                    conn.close()
                else:
                    front._pool.give(url, conn)
                return (resp.status,
                        resp.headers.get("Content-Type",
                                         "application/json"),
                        body, resp.headers.get("Retry-After"))

        def _reply_raw(self, code: int, ctype: str, body: bytes,
                       headers: dict) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in headers.items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

    return FleetHandler


# -------------------------------------------------------------------- CLI

def main(argv: list[str] | None = None) -> int:
    import argparse
    import signal

    parser = argparse.ArgumentParser(
        prog="dptpu-fleet",
        description="multi-replica serving front: consistent-hash "
                    "session routing, health-driven membership, "
                    "failover, autoscale")
    mode = parser.add_mutually_exclusive_group(required=True)
    mode.add_argument("--attach", nargs="+", metavar="URL",
                      help="route over existing dptpu-serve replicas "
                           "(pure-router mode)")
    mode.add_argument("--replicas", type=int, default=None,
                      help="local mode: spawn N dptpu-serve children "
                           "and supervise them")
    parser.add_argument("--run-dir", default=None,
                        help="local mode: the replicas' training run dir")
    parser.add_argument("--torch", default=None, metavar="PTH",
                        help="local mode: torch checkpoint instead of a "
                             "run dir")
    parser.add_argument("--fresh-init", default=None, metavar="SPEC",
                        const="64", nargs="?",
                        help="local mode: fresh-init replicas (dev/chaos "
                             "only; see dptpu-serve --fresh-init)")
    parser.add_argument("--serve-args", default="", metavar="ARGS",
                        help="extra dptpu-serve flags for each replica, "
                             "one shell-quoted string (e.g. "
                             "'--warmup --aot-cache /c --max-batch 8')")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8800)
    parser.add_argument("--poll-interval-s", type=float, default=1.0)
    parser.add_argument("--target-p99-ms", type=float, default=250.0,
                        help="the autoscale plan's latency target")
    parser.add_argument("--min-replicas", type=int, default=1)
    parser.add_argument("--max-replicas", type=int, default=8)
    parser.add_argument("--autoscale", action="store_true",
                        help="local mode: actuate /fleet/plan with "
                             "escalate/disarm hysteresis")
    parser.add_argument("--max-restarts", type=int, default=3,
                        help="respawn budget per local replica slot")
    parser.add_argument("--workdir", default="./fleet",
                        help="local mode: replica logs land here")
    parser.add_argument("--events-dir", default=None, metavar="DIR",
                        help="flight-recorder run dir for fleet events "
                             "(replica_up/down, failover, "
                             "scale_decision) — dptpu-doctor reads it")
    args = parser.parse_args(argv)

    log = None
    if args.events_dir:
        log = events_lib.configure(args.events_dir)
    manager = None
    n = 0
    if args.replicas is not None:
        n = args.replicas
        src = []
        if args.run_dir:
            src = ["--run-dir", args.run_dir]
        elif args.torch:
            src = ["--torch", args.torch]
        elif args.fresh_init:
            src = ["--fresh-init", args.fresh_init]
        else:
            parser.error("local mode needs --run-dir, --torch or "
                         "--fresh-init for the replicas")
        template = ([sys.executable, "-m", "distributedpytorch_tpu.serve"]
                    + src + shlex.split(args.serve_args))
        manager = LocalManager(template, workdir=args.workdir,
                               max_restarts=args.max_restarts)
    front = FleetFront(attach=args.attach, manager=manager, replicas=n,
                       poll_interval_s=args.poll_interval_s,
                       target_p99_ms=args.target_p99_ms,
                       min_replicas=args.min_replicas,
                       max_replicas=args.max_replicas,
                       autoscale=args.autoscale)
    front.start()
    httpd = ThreadingHTTPServer((args.host, args.port),
                                make_fleet_handler(front))

    def on_signal(signum, frame):
        # shutdown() must come from another thread than serve_forever's
        threading.Thread(target=httpd.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)
    print(json.dumps({"fleet": f"http://{args.host}:{args.port}",
                      "mode": front.mode,
                      "replicas": (n if front.mode == "local"
                                   else len(args.attach or [])),
                      "autoscale": front.autoscale}), flush=True)
    try:
        httpd.serve_forever()
    finally:
        httpd.server_close()
        front.stop()
        if log is not None:
            events_lib.release(log)
        print(json.dumps({"stopped": True, "health": front.health()}),
              flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
