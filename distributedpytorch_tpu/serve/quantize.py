"""Post-training int8 weight quantization for the serve forward.

The serve fleet's cost is dominated by two terms: HBM residency (one
~200 MB f32 param set per resident generation bounds how many replicas
a chip oversubscribes) and HBM bandwidth (the forward re-reads every
kernel per dispatch).  Weight-only int8 quantization attacks both at
once — the integer-only-inference playbook (arXiv 1712.05877), taken
only as far as measurement justifies:

* **per-channel symmetric int8 weights** for conv/dense kernels: each
  output channel gets one f32 scale (``scale = max|w| / 127``), the
  kernel stores as int8 — 4x smaller in HBM, and the compiled forward
  reads int8 bytes;
* **dequant-at-use inside the jitted forward**: the int8 kernels are
  closure constants of the SAME jitted forward the f32 predictor
  compiles; materialization (``q.astype(f32) * scale``) happens inside
  the trace, so XLA fuses the int8 read + convert + scale into the
  consuming conv/matmul — the weights never exist as f32 in HBM;
* **everything else stays f32**: biases, BN scale/bias/batch-stats,
  activations, the loss-side sigmoid.  BatchNorm in this architecture
  is a separate (unfused) layer, so there is no conv+BN product to fold
  — the BN arithmetic stays exactly the f32 predictor's.  Weight-only
  is deliberately the first rung: it needs no calibration data, its
  error is bounded per-channel, and it keeps activation dtype flow
  identical to the audited f32 forward.

The regime is **declared, not vibes**: :class:`QuantPolicy` names the
one new dtype-flow pattern quantization introduces — an int8→f32
dequantization convert consumed by the scale ``mul``
(:data:`QUANT_DEQUANT_PRIMS`) — and jaxaudit's JA002 audits the
quantized programs against ``QuantPolicy.ja002_allow()``.  Zero
findings under the policy means every int8 upcast in the program is a
declared dequantization point; the same program audited under the
strict default allowlist FAILS (the ``mul`` is not a default
accumulation prim), which is what proves the declaration load-bearing.
The canonical ``serve_forward_int8_b1/b8`` + ``decode_int8`` programs
pin this (and the ~4x const-byte shrink) as checked-in cpu8 compile
contracts.

Parity is banded, not assumed: tests/test_quantize.py pins the int8
probability maps within a small absolute band of the f32 forward across
every ladder bucket, and mask IoU >= 0.99 on the serve fixtures — the
acceptance gate a quantized deploy must clear before it canaries
(sessions, hot-swap and the bucket ladder all compose: a quantized
canary rolls back like any other generation).
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np

from ..predict import Predictor

#: f32 primitives the dequantization introduces on upcast int8 data,
#: beyond the strict default allowlist (analysis/ir.py
#: DEFAULT_F32_ACCUM_ALLOW): the per-channel scale multiply
#: ``q.astype(f32) * scale`` — the ONE arithmetic op between an int8
#: kernel constant and the conv/matmul that consumes it.  Deliberately
#: nothing else: any other f32 math appearing on an int8 upcast (a
#: dequantized kernel leaking into elementwise chains, a second
#: dequantization site) still fails JA002 under the policy.
QUANT_DEQUANT_PRIMS = frozenset({"mul"})


@jax.tree_util.register_pytree_node_class
class QTensor:
    """One quantized kernel: int8 values + per-channel f32 scales.

    Two protocols make it a drop-in kernel leaf:

    * **pytree node** — a params tree holding QTensor leaves flattens
      into its int8/f32 arrays, so checkpoint digests, tree
      serialization and jit argument passing all see the raw (4x
      smaller) buffers;
    * **``__jax_array__``** — the dequant-at-USE seam.  flax's dtype
      promotion calls ``jnp.asarray`` on every kernel the moment a
      layer consumes it, which dispatches here: the dequantization
      (``convert_element_type`` + ``mul``) is traced INSIDE the jitted
      forward at the exact use site, the int8 array rides the program
      as its baked constant, and XLA fuses the int8 read + scale into
      the consuming conv/matmul.  Laziness matters: a program that
      never touches a kernel (the session DECODE stage vs the backbone)
      never bakes it — each stage's const bytes stay exactly its own
      kernels, quantized.

    The float form must never materialize host-side: ``dequantize`` is
    jnp on purpose (numpy arithmetic on closure constants executes
    EAGERLY inside a trace and would bake the folded f32 kernel back in,
    silently undoing the whole quantization).
    """

    __slots__ = ("q", "scale")

    def __init__(self, q, scale):
        self.q = q
        self.scale = scale

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    @property
    def dtype(self):
        """The DEQUANTIZED dtype — what dtype-promotion logic (flax's
        ``promote_dtype``) must see, so a quantized kernel promotes
        exactly like the float kernel it replaces."""
        return np.dtype(self.scale.dtype)

    def dequantize(self):
        """``q * scale`` in the scale's dtype, as jnp ops (see class
        docstring for why never numpy)."""
        import jax.numpy as jnp

        scale = jnp.asarray(self.scale)
        return jnp.asarray(self.q).astype(scale.dtype) * scale

    # jnp.asarray(qtensor) -> the traced dequantized form.  This is the
    # one seam flax (and any jnp consumer) reaches a kernel through.
    __jax_array__ = dequantize

    def __repr__(self):
        return (f"QTensor(int8{list(self.q.shape)}, "
                f"scale{list(self.scale.shape)})")


def _is_qtensor(x) -> bool:
    return isinstance(x, QTensor)


@dataclasses.dataclass(frozen=True)
class QuantPolicy:
    """One weight-quantization regime, immutable and JSON-able (the
    ``train.precision.Policy`` convention, serve-side).

    ``weight_dtype`` is what quantized kernels store as; ``granularity``
    names the scale sharing (per output channel); ``symmetric`` pins the
    zero-point-free form (q = round(w/scale), no offset — the form whose
    dequant is one multiply, which is exactly what ``ja002_allow``
    declares)."""

    weight_dtype: str = "int8"
    granularity: str = "per_channel"
    symmetric: bool = True

    #: int8 range bound: symmetric [-127, 127] (never -128 — a symmetric
    #: scale must map +max and -max to the same magnitude)
    QMAX = 127

    def ja002_allow(self) -> frozenset:
        """The JA002 allowlist for programs built under this policy:
        the strict default set plus :data:`QUANT_DEQUANT_PRIMS`."""
        from ..analysis.ir import DEFAULT_F32_ACCUM_ALLOW

        return DEFAULT_F32_ACCUM_ALLOW | QUANT_DEQUANT_PRIMS

    def block(self) -> dict:
        """The bench-record ``quantization`` block (keys stable)."""
        return {
            "weight_dtype": self.weight_dtype,
            "granularity": self.granularity,
            "symmetric": self.symmetric,
        }


def quant_policy(name: str | None) -> QuantPolicy | None:
    """``model.quantization`` -> policy.  ``''``/``None``/``'none'`` is
    the unquantized regime (no policy object: every consumer's
    ``policy is None`` branch is the exact pre-quantization code path);
    ``'int8'`` is per-channel symmetric weight-only int8."""
    if not name or name == "none":
        return None
    if name == "int8":
        return QuantPolicy()
    raise ValueError(f"unknown model.quantization: {name!r} (int8 | none)")


def quantization_block(policy: QuantPolicy | None) -> dict | None:
    """The record block for bench consumers: the policy's declared
    regime, or ``None`` when unquantized (key always present in the
    record — the ``precision`` block convention)."""
    return None if policy is None else policy.block()


# ----------------------------------------------------------- quantization

def _quantize_leaf(w: np.ndarray, policy: QuantPolicy) -> QTensor:
    """Per-output-channel symmetric int8: scale over every axis but the
    last (flax kernels are ``(..., cin, cout)`` / ``(cin, cout)``), so
    each output channel's dynamic range is its own."""
    w = np.asarray(w)
    axes = tuple(range(w.ndim - 1))
    amax = np.abs(w).max(axis=axes, keepdims=True).astype(np.float32)
    # an all-zero channel (e.g. the head-inject projection's zero init)
    # quantizes to q=0 under ANY scale; 1.0 keeps the math finite
    scale = np.where(amax > 0, amax / policy.QMAX, 1.0).astype(np.float32)
    q = np.clip(np.rint(w / scale), -policy.QMAX, policy.QMAX) \
        .astype(np.int8)
    return QTensor(q, scale)


def _leaf_name(path) -> str:
    last = path[-1]
    return str(getattr(last, "key", getattr(last, "name", last)))


def quantize_params(params, policy: QuantPolicy | None = None):
    """Param tree -> the same tree with conv/dense kernels replaced by
    :class:`QTensor` leaves (everything else untouched, f32).

    Quantized: leaves named ``kernel`` with >= 2 dims — flax Conv and
    Dense weights, the HBM-dominant tensors.  Left alone: biases, BN
    scale/bias (1-D ``scale`` is BatchNorm's, never a QTensor's), and
    anything exotic a model might register.
    """
    policy = policy or QuantPolicy()

    def maybe_quantize(path, leaf):
        if _leaf_name(path) == "kernel" and getattr(leaf, "ndim", 0) >= 2:
            return _quantize_leaf(leaf, policy)
        return leaf

    return jax.tree_util.tree_map_with_path(maybe_quantize, params)


def dequantize_tree(tree):
    """Materialize every :class:`QTensor` in ``tree`` back to its float
    form (``q * scale``).  Called INSIDE the jitted forwards — the f32
    kernels exist only as fused intermediates, never in HBM."""
    return jax.tree.map(
        lambda x: x.dequantize() if _is_qtensor(x) else x,
        tree, is_leaf=_is_qtensor)


def quantize_report(params) -> dict:
    """Byte accounting of a (possibly quantized) param tree: how much
    HBM the quantization actually saved (the ~4x the contracts pin)."""
    q_bytes = f_bytes = 0
    n_q = n_f = 0

    def visit(x):
        nonlocal q_bytes, f_bytes, n_q, n_f
        if _is_qtensor(x):
            q_bytes += x.q.size * 1 + np.asarray(x.scale).nbytes
            n_q += 1
        else:
            f_bytes += int(np.prod(getattr(x, "shape", ()),
                                   dtype=np.int64)
                           * np.dtype(x.dtype).itemsize) \
                if hasattr(x, "dtype") else 0
            n_f += 1
        return x

    jax.tree.map(visit, params, is_leaf=_is_qtensor)
    return {"quantized_leaves": n_q, "float_leaves": n_f,
            "quantized_bytes": int(q_bytes), "float_bytes": int(f_bytes)}


# -------------------------------------------------------------- predictor

class QuantizedPredictor(Predictor):
    """A :class:`predict.Predictor` whose kernels live as int8 + scales.

    Identical API and identical program structure — the encode/decode
    split, the bucket ladder, sessions, hot-swap and the AOT cache all
    compose, because the predictor itself changes NOTHING: the
    :class:`QTensor` leaves in ``params`` dequantize at use via
    ``__jax_array__`` inside whichever forward consumes them.
    ``quant_policy`` rides along for the audit/bench surfaces
    (``ja002_allow``, the ``quantization`` record block, the AOT cache
    fingerprint)."""

    def __init__(self, model, params, batch_stats, *,
                 quant_policy: QuantPolicy | None = None, **kwargs):
        self.quant_policy = quant_policy or QuantPolicy()
        super().__init__(model, params, batch_stats, **kwargs)


def quantize_predictor(predictor: Predictor,
                       policy: QuantPolicy | None = None
                       ) -> QuantizedPredictor:
    """Quantize a live predictor's weights into a drop-in replacement.

    The serving configuration (resolution, relax, guidance family, ...)
    carries over — the same inheritance seam as
    ``serve.swap.load_swap_predictor`` — so the quantized predictor's
    compiled ladder is shape-compatible with the service it replaces
    (a quantized generation can canary into a live f32 fleet and roll
    back).  The f32 kernels are not retained: the returned predictor's
    ``params`` hold the int8/scales tree.
    """
    policy = policy or QuantPolicy()
    kwargs = {attr: getattr(predictor, attr)
              for attr in ("resolution", "relax", "zero_pad", "alpha",
                           "guidance", "in_channels")}
    kwargs["mesh"] = getattr(predictor, "mesh", None)
    return QuantizedPredictor(
        predictor.model,
        quantize_params(predictor.params, policy),
        predictor.batch_stats, quant_policy=policy, **kwargs)
