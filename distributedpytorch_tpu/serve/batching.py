"""Power-of-two micro-batch bucketing for the inference service.

XLA compiles one program per input shape.  A naive batcher that forwards
whatever N requests happen to be pending compiles a fresh program for every
distinct N — under bursty traffic that is a compile storm at exactly the
moment latency matters most.  Rounding every pending batch UP to a fixed
power-of-two bucket (1/2/4/8/... lanes, fixed 512x512 spatial shape) bounds
the program count at ``log2(max_batch) + 1`` forever: each bucket compiles
exactly once, every later batch rides the jit cache, and the padding waste
is < 2x in the worst case (amortized far less — a full bucket has none).

The padded lanes are dead weight by construction: eval-mode BN and
per-sample attention make each output lane a function of its own input lane
only, so zero-filled padding cannot perturb the real lanes (pinned by
tests/test_serve.py::test_padding_lanes_do_not_leak) and the batcher just
slices them off.  These are pure host-side numpy functions — the service
(service.py) owns the queueing policy, this module owns the shapes.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np


def bucket_sizes(max_batch: int) -> tuple[int, ...]:
    """The ascending power-of-two bucket ladder up to ``max_batch``.

    ``max_batch`` must itself be a power of two — a ragged top bucket would
    either waste its headroom (never filled) or round up past the declared
    maximum (violating the operator's HBM budget).
    """
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    if max_batch & (max_batch - 1):
        raise ValueError(
            f"max_batch must be a power of two, got {max_batch} "
            "(the bucket ladder doubles; a ragged top bucket would "
            "over- or under-shoot it)")
    sizes = []
    b = 1
    while b <= max_batch:
        sizes.append(b)
        b *= 2
    return tuple(sizes)


def bucket_for(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket holding ``n`` requests.

    ``buckets`` is the ascending ladder from :func:`bucket_sizes`; asking
    for more than the top bucket is a caller bug (the service never drains
    more than ``max_batch`` requests per batch).
    """
    if n < 1:
        raise ValueError(f"need at least one request, got {n}")
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(
        f"{n} requests exceed the top bucket {buckets[-1]} — the batcher "
        "must split the drain, not grow the program")


def pad_to_bucket(stack: np.ndarray, bucket: int) -> np.ndarray:
    """(n, H, W, C) request stack -> (bucket, H, W, C), zero-filled lanes.

    Zero lanes (not repeats of a real request) so a masking bug downstream
    surfaces as an obviously-wrong all-background mask instead of silently
    serving one user's result to another.
    """
    n = stack.shape[0]
    if n > bucket:
        raise ValueError(f"{n} requests do not fit bucket {bucket}")
    if n == bucket:
        return stack
    padded = np.zeros((bucket, *stack.shape[1:]), stack.dtype)
    padded[:n] = stack
    return padded


def unpad(results: np.ndarray, n: int) -> np.ndarray:
    """Mask the padded lanes back out: keep only the ``n`` real results."""
    return results[:n]
