"""Ops surface of the inference service: counters + latency percentiles.

A batcher that silently sheds or silently retraces is indistinguishable
from a healthy one at the API — the metrics are the only place the
difference shows.  Everything here is cheap host-side accounting sampled
on the request path (no device work), snapshot-read by the ``/stats`` and
``/healthz`` endpoints and by ``bench.py --serve``.

Latency is end-to-end request latency (submit -> mask handed back), the
number a client actually experiences: queue wait + batching wait + forward
+ paste-back.  Percentiles use the nearest-rank rule shared with the train
side (:func:`utils.profiling.percentile` — StepTimer-style accounting)
over a bounded reservoir of the most recent samples, so a long-lived
service reports its CURRENT tail, not a mush of every request since boot.
"""

from __future__ import annotations

import collections
import threading

from ..utils.profiling import percentile


class ServeMetrics:
    """Thread-safe counters + a bounded latency reservoir.

    Counters (monotonic since service start):

    * ``requests``        — accepted into the queue
    * ``completed``       — answered with a mask
    * ``failed``          — answered with an error (bad input, model error)
    * ``shed_queue_full`` — rejected at the front door (bounded queue full;
      backpressure instead of unbounded latency)
    * ``shed_deadline``   — dropped at drain time (deadline already blown;
      forwarding them would waste a lane on an answer nobody is waiting for)
    * ``batches``         — compiled-forward dispatches
    * ``retrace_failures``— steady-state recompiles the CompileWatchdog
      caught (any non-zero value means the bucket invariant broke)
    """

    def __init__(self, reservoir: int = 2048):
        self._lock = threading.Lock()
        self.requests = 0
        self.completed = 0
        self.failed = 0
        self.shed_queue_full = 0
        self.shed_deadline = 0
        self.batches = 0
        self.retrace_failures = 0
        #: per-bucket dispatch counts {bucket_size: batches}
        self.batch_buckets: collections.Counter = collections.Counter()
        #: per-bucket real-lane totals (padding waste = bucket*batches - this)
        self.batch_lanes: collections.Counter = collections.Counter()
        self._latencies = collections.deque(maxlen=reservoir)

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + n)

    def observe_batch(self, bucket: int, lanes: int) -> None:
        with self._lock:
            self.batches += 1
            self.batch_buckets[bucket] += 1
            self.batch_lanes[bucket] += lanes

    def observe_latency(self, seconds: float) -> None:
        with self._lock:
            self._latencies.append(seconds)

    def snapshot(self) -> dict:
        """One coherent dict for /stats, /healthz, and the serve bench."""
        with self._lock:
            lat = list(self._latencies)
            out = {
                "requests": self.requests,
                "completed": self.completed,
                "failed": self.failed,
                "shed_queue_full": self.shed_queue_full,
                "shed_deadline": self.shed_deadline,
                "batches": self.batches,
                "retrace_failures": self.retrace_failures,
                "batch_buckets": dict(self.batch_buckets),
                "batch_lanes": dict(self.batch_lanes),
            }
        if lat:
            out["latency_ms"] = {
                "p50": round(percentile(lat, 50.0) * 1e3, 3),
                "p99": round(percentile(lat, 99.0) * 1e3, 3),
                "max": round(max(lat) * 1e3, 3),
                "samples": len(lat),
            }
        dispatched = sum(b * c for b, c in out["batch_buckets"].items())
        if dispatched:
            out["pad_fraction"] = round(
                1.0 - sum(out["batch_lanes"].values()) / dispatched, 4)
        return out
