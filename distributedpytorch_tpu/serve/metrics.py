"""Ops surface of the inference service: counters + latency percentiles.

A batcher that silently sheds or silently retraces is indistinguishable
from a healthy one at the API — the metrics are the only place the
difference shows.  Everything here is cheap host-side accounting sampled
on the request path (no device work), snapshot-read by the ``/stats`` and
``/healthz`` endpoints and by ``bench.py --serve``.

Storage lives in the process-wide telemetry registry
(:mod:`telemetry.registry`) under stable Prometheus names
(``serve_requests_total``, ``serve_batches_total{bucket=...}``,
``serve_latency_seconds``, ...), so ``GET /metrics`` exports the serve
counters and the train-side goodput gauges from ONE surface.  The
:class:`ServeMetrics` view stays per-service: each instance snapshots the
registry values at construction and reports deltas, preserving the
"monotonic since service start" contract even when several services (or
test cases) share one process — the registry keeps process-lifetime
totals, the service reports its own.

Latency is end-to-end request latency (submit -> mask handed back), the
number a client actually experiences: queue wait + batching wait + forward
+ paste-back.  Percentiles use the nearest-rank rule shared with the train
side (:func:`utils.profiling.percentile` — StepTimer-style accounting)
over a bounded reservoir of the most recent samples, so a long-lived
service reports its CURRENT tail, not a mush of every request since boot.
"""

from __future__ import annotations

import collections
import threading

from ..telemetry.registry import MetricsRegistry, get_registry
from ..utils.profiling import percentile

#: counter slug -> help string (also fixes the exported metric set)
_COUNTERS = {
    "requests": "requests accepted into the queue",
    "completed": "requests answered with a mask",
    "failed": "requests answered with an error",
    "shed_queue_full": "requests rejected at the front door (queue full)",
    "shed_session_lane": "requests rejected because one session "
                         "overfilled its per-session lane",
    "shed_deadline": "requests dropped at drain time (deadline blown)",
    "batches": "compiled-forward dispatches",
    "retrace_failures": "steady-state recompiles the watchdog caught",
}


class ServeMetrics:
    """Per-service view over registry-backed counters + a bounded latency
    reservoir.

    Counters (monotonic since service start; process-lifetime totals live
    in the registry as ``serve_<name>_total``):

    * ``requests``        — accepted into the queue
    * ``completed``       — answered with a mask
    * ``failed``          — answered with an error (bad input, model error)
    * ``shed_queue_full`` — rejected at the front door (bounded queue full;
      backpressure instead of unbounded latency)
    * ``shed_deadline``   — dropped at drain time (deadline already blown;
      forwarding them would waste a lane on an answer nobody is waiting for)
    * ``batches``         — compiled-forward dispatches
    * ``retrace_failures``— steady-state recompiles the CompileWatchdog
      caught (any non-zero value means the bucket invariant broke)
    """

    def __init__(self, reservoir: int = 2048,
                 registry: MetricsRegistry | None = None):
        self._registry = registry or get_registry()
        self._lock = threading.Lock()
        self._c = {name: self._registry.counter(f"serve_{name}_total", help)
                   for name, help in _COUNTERS.items()}
        #: registry values at service start — the delta IS this service
        self._base = {name: c.value for name, c in self._c.items()}
        #: per-bucket dispatch counts {bucket_size: batches} (per-service;
        #: mirrored into serve_batches_total{bucket=...})
        self.batch_buckets: collections.Counter = collections.Counter()
        #: per-bucket real-lane totals (padding waste = bucket*batches - this)
        self.batch_lanes: collections.Counter = collections.Counter()
        self._hist = self._registry.histogram(
            "serve_latency_seconds",
            "end-to-end request latency (submit -> mask)",
            reservoir=reservoir)
        self._latencies = collections.deque(maxlen=reservoir)
        #: per-bucket registry children, cached — the bucket ladder is a
        #: small fixed set and the dispatch path must not pay two
        #: registry get-or-create lookups per batch
        self._bucket_children: dict[int, tuple] = {}

    def __getattr__(self, name: str) -> int:
        # counter reads (metrics.requests, .shed_deadline, ...) — delta
        # against the service-start baseline.  __getattr__ only fires for
        # names not found normally, so real attributes stay fast.
        c = self.__dict__.get("_c", {}).get(name)
        if c is None:
            raise AttributeError(name)
        return int(c.value - self.__dict__["_base"][name])

    def count(self, name: str, n: int = 1) -> None:
        self._c[name].inc(n)

    def observe_batch(self, bucket: int, lanes: int) -> None:
        children = self._bucket_children.get(bucket)
        if children is None:
            children = self._bucket_children[bucket] = (
                self._registry.counter(
                    "serve_batch_dispatches_total",
                    "dispatches per bucket size",
                    labels={"bucket": bucket}),
                self._registry.counter(
                    "serve_batch_lanes_total",
                    "real lanes per bucket size",
                    labels={"bucket": bucket}))
        self._c["batches"].inc()
        children[0].inc()
        children[1].inc(lanes)
        with self._lock:
            self.batch_buckets[bucket] += 1
            self.batch_lanes[bucket] += lanes

    def observe_latency(self, seconds: float) -> None:
        self._hist.observe(seconds)
        with self._lock:
            self._latencies.append(seconds)

    def snapshot(self) -> dict:
        """One snapshot dict for /stats, /healthz, and the serve bench.
        Counter reads are lock-free against the registry, so adjacent
        fields can tear by a request under concurrent load (e.g.
        ``batch_buckets`` momentarily summing one past ``batches``) —
        each value is individually exact, the set is not a barrier."""
        with self._lock:
            lat = list(self._latencies)
            buckets = dict(self.batch_buckets)
            lanes = dict(self.batch_lanes)
        out = {name: int(self._c[name].value - self._base[name])
               for name in _COUNTERS}
        out["batch_buckets"] = buckets
        out["batch_lanes"] = lanes
        if lat:
            out["latency_ms"] = {
                "p50": round(percentile(lat, 50.0) * 1e3, 3),
                "p99": round(percentile(lat, 99.0) * 1e3, 3),
                "max": round(max(lat) * 1e3, 3),
                "samples": len(lat),
            }
        dispatched = sum(b * c for b, c in out["batch_buckets"].items())
        if dispatched:
            out["pad_fraction"] = round(
                1.0 - sum(out["batch_lanes"].values()) / dispatched, 4)
        return out
