"""Routing math for the fleet front: consistent-hash ring + least-loaded.

Pure, stdlib-only, no I/O and no locks — the fleet (serve/fleet.py) owns
membership and concurrency; this module owns only the two routing
questions, each a pure function of its inputs:

* **Which replica owns this session?**  :class:`HashRing` — consistent
  hashing with virtual nodes.  Sessions are generation- and cache-affine
  by design (serve/sessions.py): a session's encoded features live in
  ONE replica's HBM, so the router's job is to keep sending a session's
  clicks where its features are.  A consistent hash makes membership
  changes cheap: adding/removing one of N replicas moves only ~K/N of K
  sessions (the property test in tests/test_fleet.py pins the bound),
  and a moved session is not an error — its first click on the new
  replica misses ``covers()`` and degrades to one counted re-encode.
* **Which replica for a stateless request?**  :func:`least_loaded` —
  pick the replica with the most queue headroom, tie-broken by p99 then
  id, using the queue-depth/p99 signals every replica already exposes
  on ``/healthz``.

Hash points come from ``hashlib.blake2b`` over utf-8 bytes — NOT
Python's ``hash()``, which is salted per process (PYTHONHASHSEED) and
would send the same session to different replicas from different front
processes.  Determinism across processes is a routing correctness
property here, not a nicety: a restarted front must rebuild the SAME
ring or every live session pays a spurious re-encode.
"""

from __future__ import annotations

import bisect
import hashlib

#: virtual nodes per replica: enough that the max/min key-load ratio
#: over a handful of replicas stays small (tests pin < 1.8 at 10k keys)
#: while keeping the ring a few hundred points — lookups stay one
#: bisect over a list that rebuilds in microseconds on membership change
DEFAULT_VNODES = 96


def _point(data: str) -> int:
    """Stable 64-bit hash point for a ring position or a key."""
    return int.from_bytes(
        hashlib.blake2b(data.encode("utf-8"), digest_size=8).digest(),
        "big")


class HashRing:
    """Consistent-hash ring over replica ids, with virtual nodes.

    >>> ring = HashRing(["a", "b", "c"])
    >>> ring.lookup("session-42")            # owning replica
    >>> ring.candidates("session-42")        # failover order, all nodes

    The ring is immutable-by-convention between :meth:`add`/:meth:`remove`
    calls (the fleet rebuilds under its registry lock and swaps the whole
    object in); lookups never mutate.
    """

    def __init__(self, nodes=(), vnodes: int = DEFAULT_VNODES):
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        self.vnodes = vnodes
        self._nodes: set[str] = set()
        #: sorted hash points and their parallel owner list (bisect keys)
        self._points: list[int] = []
        self._owners: list[str] = []
        for n in nodes:
            self.add(n)

    def __len__(self) -> int:
        return len(self._nodes)

    def __contains__(self, node: str) -> bool:
        return node in self._nodes

    @property
    def nodes(self) -> set[str]:
        return set(self._nodes)

    def add(self, node: str) -> None:
        """Insert ``node``'s virtual points; idempotent."""
        if node in self._nodes:
            return
        self._nodes.add(node)
        for v in range(self.vnodes):
            p = _point(f"{node}#{v}")
            i = bisect.bisect_left(self._points, p)
            self._points.insert(i, p)
            self._owners.insert(i, node)

    def remove(self, node: str) -> None:
        """Drop ``node``'s virtual points; idempotent.  Only the removed
        node's key ranges move (to each range's clockwise successor) —
        the minimal-disruption property the whole design rides on."""
        if node not in self._nodes:
            return
        self._nodes.discard(node)
        keep = [(p, o) for p, o in zip(self._points, self._owners)
                if o != node]
        self._points = [p for p, _ in keep]
        self._owners = [o for _, o in keep]

    def lookup(self, key: str) -> str | None:
        """The replica owning ``key`` (first point clockwise), or None on
        an empty ring."""
        if not self._points:
            return None
        i = bisect.bisect_right(self._points, _point(key))
        if i == len(self._points):
            i = 0  # wrap: past the last point owns back to the first
        return self._owners[i]

    def candidates(self, key: str, n: int | None = None) -> list[str]:
        """Distinct replicas in ring order starting at ``key``'s owner —
        the failover sequence: a request whose primary died mid-flight
        retries on ``candidates(key)[1]``.  ``n`` caps the list (default:
        every node, each exactly once)."""
        if not self._points:
            return []
        want = len(self._nodes) if n is None else min(n, len(self._nodes))
        out: list[str] = []
        seen: set[str] = set()
        start = bisect.bisect_right(self._points, _point(key))
        for off in range(len(self._points)):
            owner = self._owners[(start + off) % len(self._points)]
            if owner not in seen:
                seen.add(owner)
                out.append(owner)
                if len(out) >= want:
                    break
        return out


def least_loaded(loads: dict[str, dict]) -> list[str]:
    """Replica ids ordered most-headroom-first for stateless routing.

    ``loads`` maps replica id -> its last ``/healthz`` load signals:
    ``queue_depth`` / ``queue_capacity`` (the service's bounded queue)
    and ``p99_ms`` (its current tail).  Ordering: lowest queue FRACTION
    first (an 8-deep queue on a 64-slot replica beats 3-deep on a
    4-slot one), then lowest p99, then id — the id tiebreak keeps the
    order deterministic for tests and for two fronts making the same
    decision from the same snapshots.  Missing signals sort last within
    their tier (an unknown load is assumed worst, never best)."""
    def score(item):
        rid, sig = item
        depth = sig.get("queue_depth")
        cap = sig.get("queue_capacity") or 0
        frac = (depth / cap) if (depth is not None and cap) else float("inf")
        p99 = sig.get("p99_ms")
        return (frac, p99 if p99 is not None else float("inf"), rid)

    return [rid for rid, _ in sorted(loads.items(), key=score)]
