"""ServeClient: one client API over both transports.

The service runs in two configurations — in-process (a library embedding
:class:`service.InferenceService` directly) and out-of-process behind the
``python -m distributedpytorch_tpu.serve`` HTTP front end.  ServeClient
makes the two interchangeable: pass an ``InferenceService`` or a
``http://host:port`` URL, call :meth:`predict` either way, get the same
(H, W) float32 mask and the same exception taxonomy (QueueFullError when
shed, DeadlineExceededError when expired, ValueError for bad clicks).

The HTTP wire is dependency-free JSON: arrays travel as
``{"shape": [...], "dtype": "...", "b64": <base64 of raw C-order bytes>}``
— no pickle (never unpickle network input), no image re-encode on the hot
path, stdlib-only on both ends.
"""

from __future__ import annotations

import base64
import json
import threading
import time
import urllib.error
import urllib.request
from typing import Any

import numpy as np

from .service import (
    DeadlineExceededError,
    InferenceService,
    QueueFullError,
    ServiceUnhealthyError,
    SessionLaneFullError,
)

#: dtypes the wire accepts — closed set, so a hostile payload cannot name
#: an object dtype and smuggle pickled code through np.frombuffer
_WIRE_DTYPES = ("uint8", "float32", "float64", "int32", "int64", "bool")


def encode_array(arr: np.ndarray) -> dict:
    """numpy array -> JSON-safe {shape, dtype, b64(raw C-order bytes)}."""
    arr = np.ascontiguousarray(arr)
    if arr.dtype.name not in _WIRE_DTYPES:
        raise ValueError(f"dtype {arr.dtype.name} not wire-encodable "
                         f"({'|'.join(_WIRE_DTYPES)})")
    return {"shape": list(arr.shape), "dtype": arr.dtype.name,
            "b64": base64.b64encode(arr.tobytes()).decode("ascii")}


def decode_array(obj: dict) -> np.ndarray:
    """Inverse of :func:`encode_array`, validating dtype and byte count."""
    dtype = str(obj["dtype"])
    if dtype not in _WIRE_DTYPES:
        raise ValueError(f"refusing wire dtype {dtype!r}")
    shape = tuple(int(d) for d in obj["shape"])
    raw = base64.b64decode(obj["b64"])
    expected = int(np.prod(shape)) * np.dtype(dtype).itemsize
    if len(raw) != expected:
        raise ValueError(
            f"wire array byte count {len(raw)} != shape/dtype "
            f"implied {expected}")
    return np.frombuffer(raw, dtype=dtype).reshape(shape)


class HealthCache:
    """TTL cache around the device-op liveness probe: a probe every few
    seconds must not queue a device op behind every batch (nor, on a
    wedged backend, burn the probe's full timeout and leak an abandoned
    daemon thread per poll).  Shared by the HTTP front's /healthz and the
    in-process ServeClient.health path."""

    def __init__(self, ttl_s: float = 10.0):
        self.ttl_s = ttl_s
        self._lock = threading.Lock()
        self._at = -float("inf")
        self._result: tuple[bool, str] = (False, "never probed")

    def probe(self) -> tuple[bool, str]:
        from ..backend_health import device_op_alive

        with self._lock:
            if time.monotonic() - self._at >= self.ttl_s:
                self._result = device_op_alive(timeout_s=5.0)
                self._at = time.monotonic()
            return self._result


def _policies():
    """Deferred chaos.policies import (module-level would be fine — it is
    stdlib-only — but the client is also vendored into minimal consumer
    snippets, so keep its import surface lean)."""
    from ..chaos import policies

    return policies


class ReplicaDrainingError(ServiceUnhealthyError):
    """A 503 that NAMED its retry horizon (``Retry-After``): a draining
    or booting replica behind the fleet front, or the front itself with
    no live replicas yet.  Subclasses :class:`ServiceUnhealthyError` so
    existing 503 handlers keep matching; the refinement is that this
    refusal is advertised-transient — ``retry_after_s`` says when to
    come back, and :meth:`ServeClient.predict`'s shed-retry policy
    honors it."""

    def __init__(self, msg: str, retry_after_s: float | None = None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


#: HTTP status -> the in-process exception it round-trips to
_STATUS_ERRORS = {
    429: QueueFullError,
    504: DeadlineExceededError,
    503: ServiceUnhealthyError,
    400: ValueError,
}

#: error-body ``code`` -> exception, refining the status mapping: both
#: shed flavors are 429 (same retry advice), but a session-lane shed
#: means only THIS session should back off — the type must round-trip
#: (through the fleet front too: the proxy passes replica error bodies
#: through byte-for-byte, so this mapping never sees a difference)
_CODE_ERRORS = {
    "session_lane": SessionLaneFullError,
    "fleet_unavailable": ReplicaDrainingError,
}


class ServeClient:
    """Uniform client over an in-process service or a remote HTTP one.

    >>> client = ServeClient(service)                  # in-process
    >>> client = ServeClient("http://127.0.0.1:8801")  # remote
    >>> mask = client.predict(image, points)           # (H, W) float32
    """

    def __init__(self, target: InferenceService | str,
                 timeout_s: float = 60.0, shed_retries: int = 0,
                 retry_seed: int | None = None):
        if isinstance(target, str):
            self._url = target.rstrip("/")
            self._service = None
        else:
            self._url = None
            self._service = target
        self.timeout_s = timeout_s
        self._health_cache = HealthCache()
        #: fleet routing facts from the LAST HTTP reply: which replica
        #: answered (``X-Fleet-Replica``) and, when the request survived
        #: a mid-flight replica death, which dead replica it was rerouted
        #: away from (``X-Fleet-Rerouted``) — always-present keys, None
        #: off-fleet (direct single-replica serving sets no headers)
        self.last_fleet: dict = {"replica": None, "rerouted": None}
        #: ``shed_retries > 0``: QueueFullError (HTTP 429) is retried
        #: that many extra times with jittered backoff — the
        #: "retry with backoff" the shed message advises, implemented
        #: once (chaos/policies.Retry) instead of by every caller.
        #: Jitter matters here specifically: N shed clients retrying in
        #: lockstep re-arrive as the same thundering herd that got shed.
        self._retry = None if shed_retries < 1 else _policies().Retry(
            base_s=0.05, cap_s=2.0, jitter=0.5, attempts=shed_retries + 1,
            seed=retry_seed)

    def predict(self, image: np.ndarray, points: Any,
                deadline_s: float | None = None,
                session_id: str | None = None) -> np.ndarray:
        """Segment one object; blocks until the mask (or the shed/deadline
        error) comes back.  ``deadline_s`` rides to the server's batcher.

        ``session_id`` opts into session-affine serving (the interactive
        click loop): reuse one id per image-under-refinement and every
        click after the first costs only a decode on the server.  Absent
        — the default, and the whole wire story for existing callers —
        the request is stateless."""
        if self._retry is not None:
            def honor_retry_after(attempt, outcome, remaining_s):
                # a draining replica's 503 names its horizon: nap the
                # advised seconds (capped — advice, not a contract) on
                # top of the jittered backoff, through the policy's
                # injectable sleep so tests patching time.sleep see it
                after = getattr(outcome, "retry_after_s", None)
                if after:
                    self._retry.sleep(min(float(after), 5.0))

            try:
                return self._retry.call(
                    lambda: self._predict_once(image, points, deadline_s,
                                               session_id),
                    retry_on=(QueueFullError, ReplicaDrainingError),
                    on_attempt=honor_retry_after)
            except _policies().RetryBudgetExceededError as e:
                # budget spent: surface the ORIGINAL taxonomy (the last
                # QueueFullError), not the policy wrapper — callers match
                # on the shed/deadline exception types
                raise e.__cause__ from None
        return self._predict_once(image, points, deadline_s, session_id)

    def _predict_once(self, image: np.ndarray, points: Any,
                      deadline_s: float | None = None,
                      session_id: str | None = None) -> np.ndarray:
        if self._service is not None:
            # session_id only rides when given: absent stays the exact
            # pre-session call shape, so duck-typed service stands-ins
            # (tests, wrappers) keep working unchanged
            kwargs = ({} if session_id is None
                      else {"session_id": session_id})
            return self._service.predict(image, points,
                                         deadline_s=deadline_s,
                                         timeout=self.timeout_s, **kwargs)
        body: dict = {
            "image": encode_array(np.asarray(image)),
            "points": np.asarray(points, np.float64).tolist(),
        }
        if deadline_s is not None:
            body["deadline_ms"] = deadline_s * 1e3
        if session_id is not None:
            body["session_id"] = str(session_id)
        reply = self._post("/v1/predict", body)
        return decode_array(reply["mask"])

    def health(self) -> dict:
        if self._service is not None:
            # transport parity: the HTTP /healthz merges a (TTL-cached)
            # device-op liveness probe into the service state — do the
            # same here, or a wedged backend would report ok=True only
            # on the in-process path
            health = self._service.health()
            alive, why = self._health_cache.probe()
            health["backend_alive"] = alive
            if not alive:
                health["ok"] = False
                health["unhealthy_reason"] = (
                    health.get("unhealthy_reason") or why)
            return health
        return self._get("/healthz")

    def stats(self) -> dict:
        if self._service is not None:
            return self._service.metrics.snapshot()
        return self._get("/stats")

    # ------------------------------------------------------------ transport

    def _note_fleet(self, headers) -> None:
        self.last_fleet = {"replica": headers.get("X-Fleet-Replica"),
                           "rerouted": headers.get("X-Fleet-Rerouted")}

    def _request(self, req: urllib.request.Request) -> dict:
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                self._note_fleet(r.headers)
                return json.loads(r.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            self._note_fleet(e.headers)
            retry_after = None
            try:
                retry_after = float(e.headers.get("Retry-After"))
            except (TypeError, ValueError):
                pass
            detail, code, parsed = "", None, False
            try:
                payload = json.loads(e.read().decode("utf-8"))
                detail = payload.get("error", "")
                code = payload.get("code")
                parsed = True
            except Exception:
                pass
            if not parsed and e.code >= 500:
                # a 5xx whose body is NOT our taxonomy is an unknown
                # failure from an unknown layer (a proxy's bare error
                # page, a half-written reply): the request may or may
                # not have executed, so it must surface as untyped —
                # never as a shed the retry policy would happily replay
                raise RuntimeError(
                    f"serve endpoint returned HTTP {e.code} with an "
                    f"unparseable body — not retrying a request whose "
                    f"server-side fate is unknown") from e
            exc = _CODE_ERRORS.get(code) or _STATUS_ERRORS.get(e.code)
            if exc is ServiceUnhealthyError and retry_after is not None:
                # a 503 naming its horizon is a draining/booting replica
                # (or the fleet front between replicas) — the typed,
                # advertised-transient refinement
                exc = ReplicaDrainingError
            if exc is ReplicaDrainingError:
                raise exc(detail or f"HTTP {e.code}",
                          retry_after_s=retry_after) from None
            if exc is not None:
                err = exc(detail or f"HTTP {e.code}")
                if retry_after is not None:
                    err.retry_after_s = retry_after
                raise err from None
            raise RuntimeError(
                f"serve endpoint returned HTTP {e.code}: {detail}") from e

    def _post(self, path: str, body: dict) -> dict:
        data = json.dumps(body).encode("utf-8")
        return self._request(urllib.request.Request(
            self._url + path, data=data, method="POST",
            headers={"Content-Type": "application/json"}))

    def _get(self, path: str) -> dict:
        # /healthz answers 503 with a JSON body when unhealthy — that body
        # IS the answer for a probe, not an error to raise, so read it
        # directly instead of funneling through the exception mapping
        try:
            with urllib.request.urlopen(self._url + path,
                                        timeout=self.timeout_s) as r:
                return json.loads(r.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            try:
                return json.loads(e.read().decode("utf-8"))
            except Exception:
                return {"ok": False,
                        "unhealthy_reason": f"HTTP {e.code} with no body"}
