"""On-device batch augmentation — jit-able, fused into the train step.

The reference did all augmentation on CPU in loader workers
(custom_transforms.py via cv2).  The geometry-heavy, mask-dependent parts
(crop-from-mask, extreme points, n-ellipse) stay host-side here too (dynamic
shapes, SURVEY §7 hard parts) — but the *fixed-shape* augmentations can run
on device inside the compiled step, where they are effectively free (fused
into the first conv's input read) and save host CPU for decoding:

* :func:`random_hflip` — per-sample coin-flip horizontal mirror;
* :func:`random_crop` — static-size random window (pad-then-crop jitter);
* :func:`normalize` — channel mean/std normalization (the [0,255]->net-input
  scaling the reference folded into its external model);
* :func:`make_device_augment` — composes them into an
  ``(batch, rng) -> batch`` fn accepted by ``make_train_step(augment=...)``.

All take NHWC batches and a PRNG key; per-sample randomness comes from
splitting the key over the batch dim.  Label-coupled ops transform ``concat``
and ``crop_gt``/``crop_void`` consistently.
"""

from __future__ import annotations

from typing import Callable, Mapping, Sequence

import jax
import jax.numpy as jnp

Batch = Mapping[str, jax.Array]

#: keys flipped/cropped together (input + label + void must stay aligned)
_SPATIAL_KEYS = ("concat", "crop_gt", "crop_void")


def _spatial(batch: Batch) -> list[str]:
    return [k for k in _SPATIAL_KEYS if k in batch]


def random_hflip(batch: Batch, rng: jax.Array, p: float = 0.5) -> dict:
    """Mirror each sample left-right with probability ``p`` — the device
    form of transforms.RandomHorizontalFlip (same coin per sample across
    input/label/void)."""
    keys = _spatial(batch)
    n = batch[keys[0]].shape[0]
    coins = jax.random.uniform(rng, (n,)) < p
    out = dict(batch)
    for k in keys:
        v = batch[k]
        flipped = jnp.flip(v, axis=2 if v.ndim >= 3 else 1)
        shape = (n,) + (1,) * (v.ndim - 1)
        out[k] = jnp.where(coins.reshape(shape), flipped, v)
    return out


def random_crop(batch: Batch, rng: jax.Array, pad: int = 16) -> dict:
    """Translation jitter: reflect-pad by ``pad`` then take a random
    same-size window per sample.  Static output shapes (XLA-friendly);
    label/void crop with the same offsets."""
    keys = _spatial(batch)
    n, h, w = batch[keys[0]].shape[:3]
    oy = jax.random.randint(rng, (n,), 0, 2 * pad + 1)
    ox = jax.random.randint(jax.random.fold_in(rng, 1), (n,), 0, 2 * pad + 1)
    out = dict(batch)
    for k in keys:
        v = batch[k]
        squeeze = v.ndim == 3
        if squeeze:
            v = v[..., None]
        pw = ((0, 0), (pad, pad), (pad, pad), (0, 0))
        vp = jnp.pad(v, pw, mode="reflect")

        def crop_one(img, y, x):
            return jax.lax.dynamic_slice(
                img, (y, x, 0), (h, w, img.shape[-1]))

        cropped = jax.vmap(crop_one)(vp, oy, ox)
        out[k] = cropped[..., 0] if squeeze else cropped
    return out


def normalize(batch: Batch,
              mean: Sequence[float] = (0.0,),
              std: Sequence[float] = (255.0,)) -> dict:
    """Channel-wise ``(x - mean) / std`` on the input only."""
    out = dict(batch)
    x = batch["concat"]
    m = jnp.asarray(mean, x.dtype)
    s = jnp.asarray(std, x.dtype)
    out["concat"] = (x - m) / s
    return out


def make_preprocess(
    mean: Sequence[float] = (0.0,),
    std: Sequence[float] = (255.0,),
) -> Callable[[Batch], dict]:
    """Deterministic input preprocessing, shared by train AND eval.

    Normalization must be identical on both paths — pass the result to
    ``make_eval_step(preprocess=...)`` whenever the train augment includes
    mean/std, or validation runs on out-of-distribution inputs and the
    best-checkpoint gate is corrupted silently.
    """

    def preprocess(batch: Batch) -> dict:
        return normalize(batch, mean, std)

    return preprocess


def make_device_augment(
    hflip: bool = True,
    crop_pad: int = 0,
    mean: Sequence[float] | None = None,
    std: Sequence[float] | None = None,
) -> Callable[[Batch, jax.Array], dict]:
    """Compose the enabled stages into one ``(batch, rng) -> batch`` fn for
    ``make_train_step(augment=...)``.  Everything traces into the same XLA
    program as the forward pass.

    If ``mean``/``std`` are given, ALSO pass
    ``make_preprocess(mean, std)`` to ``make_eval_step`` — see
    :func:`make_preprocess`.  Omitted ``std`` defaults to 255 (the
    documented [0,255] -> net-input scaling), matching :func:`normalize`.
    """

    def augment(batch: Batch, rng: jax.Array) -> dict:
        b = dict(batch)
        r1, r2 = jax.random.split(rng)
        if hflip:
            b = random_hflip(b, r1)
        if crop_pad:
            b = random_crop(b, r2, pad=crop_pad)
        if mean is not None or std is not None:
            b = normalize(b, mean if mean is not None else (0.0,),
                          std if std is not None else (255.0,))
        return b

    return augment
