"""On-device batch augmentation — jit-able, fused into the train step.

The reference did all augmentation on CPU in loader workers
(custom_transforms.py via cv2).  The geometry-heavy, mask-dependent parts
(crop-from-mask, extreme points, n-ellipse) stay host-side here too (dynamic
shapes, SURVEY §7 hard parts) — but the *fixed-shape* augmentations can run
on device inside the compiled step, where they are effectively free (fused
into the first conv's input read) and save host CPU for decoding:

* :func:`random_hflip` — per-sample coin-flip horizontal mirror;
* :func:`random_crop` — static-size random window (pad-then-crop jitter);
* :func:`normalize` — channel mean/std normalization (the [0,255]->net-input
  scaling the reference folded into its external model);
* :func:`make_device_augment` — composes them into an
  ``(batch, rng) -> batch`` fn accepted by ``make_train_step(augment=...)``.

All take NHWC batches and a PRNG key; per-sample randomness comes from
splitting the key over the batch dim.  Label-coupled ops transform ``concat``
and ``crop_gt``/``crop_void`` consistently.
"""

from __future__ import annotations

from typing import Callable, Mapping, Sequence

import jax
import jax.numpy as jnp

Batch = Mapping[str, jax.Array]

#: keys flipped/cropped together (input + label + void must stay aligned)
_SPATIAL_KEYS = ("concat", "crop_gt", "crop_void")


def _spatial(batch: Batch) -> list[str]:
    return [k for k in _SPATIAL_KEYS if k in batch]


def random_hflip(batch: Batch, rng: jax.Array, p: float = 0.5) -> dict:
    """Mirror each sample left-right with probability ``p`` — the device
    form of transforms.RandomHorizontalFlip (same coin per sample across
    input/label/void)."""
    keys = _spatial(batch)
    n = batch[keys[0]].shape[0]
    coins = jax.random.uniform(rng, (n,)) < p
    out = dict(batch)
    for k in keys:
        v = batch[k]
        flipped = jnp.flip(v, axis=2 if v.ndim >= 3 else 1)
        shape = (n,) + (1,) * (v.ndim - 1)
        out[k] = jnp.where(coins.reshape(shape), flipped, v)
    return out


def random_crop(batch: Batch, rng: jax.Array, pad: int = 16) -> dict:
    """Translation jitter: reflect-pad by ``pad`` then take a random
    same-size window per sample.  Static output shapes (XLA-friendly);
    label/void crop with the same offsets."""
    keys = _spatial(batch)
    n, h, w = batch[keys[0]].shape[:3]
    oy = jax.random.randint(rng, (n,), 0, 2 * pad + 1)
    ox = jax.random.randint(jax.random.fold_in(rng, 1), (n,), 0, 2 * pad + 1)
    out = dict(batch)
    for k in keys:
        v = batch[k]
        squeeze = v.ndim == 3
        if squeeze:
            v = v[..., None]
        pw = ((0, 0), (pad, pad), (pad, pad), (0, 0))
        vp = jnp.pad(v, pw, mode="reflect")

        def crop_one(img, y, x):
            return jax.lax.dynamic_slice(
                img, (y, x, 0), (h, w, img.shape[-1]))

        cropped = jax.vmap(crop_one)(vp, oy, ox)
        out[k] = cropped[..., 0] if squeeze else cropped
    return out


def random_scale_rotate(batch: Batch, rng: jax.Array,
                        rots: tuple[float, float] = (-20.0, 20.0),
                        scales: tuple[float, float] = (0.75, 1.25),
                        semantic: bool = False) -> dict:
    """Random rotation+scale about the center, on device — the fixed-shape
    form of transforms.ScaleNRotate (reference custom_transforms.py:76-142:
    per-sample angle/scale, cv2.warpAffine per key).

    Per-sample angle ~ U(rots), scale ~ U(scales), shared across the
    sample's keys; inverse-mapped sampling via
    ``jax.scipy.ndimage.map_coordinates`` — bilinear for the continuous
    input channels, nearest for ``crop_gt``/``crop_void`` masks, matching
    the host transform's per-key interpolation choice.  Binary masks
    (``semantic=False``) fill warped-out regions with 0 and re-binarize;
    ``semantic=True`` keeps exact class ids (order-0 samples are exact
    input values) and fills warped-out gt with 255 void so the loss
    ignores it — the host ``ScaleNRotate(semseg=True)`` border rule.
    Image channels always fill with 0 (the warpAffine default border).
    """
    keys = _spatial(batch)
    n, h, w = batch[keys[0]].shape[:3]
    k1, k2 = jax.random.split(rng)
    angles = jnp.deg2rad(jax.random.uniform(
        k1, (n,), minval=rots[0], maxval=rots[1]))
    scale = jax.random.uniform(k2, (n,), minval=scales[0], maxval=scales[1])

    yy, xx = jnp.meshgrid(jnp.arange(h, dtype=jnp.float32),
                          jnp.arange(w, dtype=jnp.float32), indexing="ij")
    cy, cx = (h - 1) / 2.0, (w - 1) / 2.0

    def src_coords(angle, s):
        # inverse map: rotate by -angle, scale by 1/s, about the center
        cos, sin = jnp.cos(angle) / s, jnp.sin(angle) / s
        sy = cy + (-sin) * (xx - cx) + cos * (yy - cy)
        sx = cx + cos * (xx - cx) + sin * (yy - cy)
        return sy, sx

    out = dict(batch)
    for k in keys:
        v = batch[k]
        squeeze = v.ndim == 3
        vv = v[..., None] if squeeze else v
        is_mask = k in ("crop_gt", "crop_void", "gt", "void_pixels")
        order = 0 if is_mask else 1
        # semantic gt: warped-out ring becomes void (ignored by the loss),
        # not class-0 background — the host semseg border rule
        cval = 255.0 if (is_mask and semantic and k in ("crop_gt", "gt")) \
            else 0.0

        def warp_one(img, angle, s, order=order, cval=cval):
            sy, sx = src_coords(angle, s)

            def chan(c):
                return jax.scipy.ndimage.map_coordinates(
                    c, [sy, sx], order=order, mode="constant", cval=cval)

            return jnp.stack([chan(img[..., i])
                              for i in range(img.shape[-1])], axis=-1)

        warped = jax.vmap(warp_one)(vv.astype(jnp.float32), angles, scale)
        if is_mask and not semantic:
            # order-0 samples are exact input values; the threshold only
            # normalizes float noise in binary {0,1} masks.  Semantic ids
            # must pass through untouched.
            warped = (warped > 0.5).astype(v.dtype)
        else:
            warped = warped.astype(v.dtype)
        out[k] = warped[..., 0] if squeeze else warped
    return out


def normalize(batch: Batch,
              mean: Sequence[float] = (0.0,),
              std: Sequence[float] = (255.0,)) -> dict:
    """Channel-wise ``(x - mean) / std`` on the input only."""
    out = dict(batch)
    x = batch["concat"]
    m = jnp.asarray(mean, x.dtype)
    s = jnp.asarray(std, x.dtype)
    out["concat"] = (x - m) / s
    return out


def make_preprocess(
    mean: Sequence[float] = (0.0,),
    std: Sequence[float] = (255.0,),
) -> Callable[[Batch], dict]:
    """Deterministic input preprocessing, shared by train AND eval.

    Normalization must be identical on both paths — pass the result to
    ``make_eval_step(preprocess=...)`` whenever the train augment includes
    mean/std, or validation runs on out-of-distribution inputs and the
    best-checkpoint gate is corrupted silently.
    """

    def preprocess(batch: Batch) -> dict:
        return normalize(batch, mean, std)

    return preprocess


def make_device_augment(
    hflip: bool = True,
    crop_pad: int = 0,
    scale_rotate: bool = False,
    rots: tuple[float, float] = (-20.0, 20.0),
    scales: tuple[float, float] = (0.75, 1.25),
    semantic: bool = False,
    mean: Sequence[float] | None = None,
    std: Sequence[float] | None = None,
    guidance_fn: Callable[[Batch, jax.Array], dict] | None = None,
) -> Callable[[Batch, jax.Array], dict]:
    """Compose the enabled stages into one ``(batch, rng) -> batch`` fn for
    ``make_train_step(augment=...)``.  Everything traces into the same XLA
    program as the forward pass.

    ``guidance_fn`` (see ops.guidance_device.make_device_guidance) runs
    after the geometric stages — the reference's stage order puts guidance
    synthesis after flip/rotate/crop (train_pascal.py:123-134), so the
    channel is derived from the label the model actually sees — and before
    normalization.

    If ``mean``/``std`` are given, ALSO pass
    ``make_preprocess(mean, std)`` to ``make_eval_step`` — see
    :func:`make_preprocess`.  Omitted ``std`` defaults to 255 (the
    documented [0,255] -> net-input scaling), matching :func:`normalize`.
    """

    def augment(batch: Batch, rng: jax.Array) -> dict:
        b = dict(batch)
        r1, r2, r3 = jax.random.split(rng, 3)
        if hflip:
            b = random_hflip(b, r1)
        if scale_rotate:
            b = random_scale_rotate(b, r3, rots=rots, scales=scales,
                                    semantic=semantic)
        if crop_pad:
            b = random_crop(b, r2, pad=crop_pad)
        if guidance_fn is not None:
            # fold_in (not a wider split) keeps r1-r3 streams identical to
            # guidance-less configs — same flips/rotations either way
            b = guidance_fn(b, jax.random.fold_in(rng, 3))
        if mean is not None or std is not None:
            b = normalize(b, mean if mean is not None else (0.0,),
                          std if std is not None else (255.0,))
        return b

    return augment
