"""On-device guidance synthesis — the 4th input channel, inside the step.

The reference synthesizes its guidance channel (extreme points -> n-ellipse +
gaussian heatmap, custom_transforms.py:30-51 via the never-committed
``dataloaders.nellipse``) per sample on the host CPU.  That is the single most
expensive host transform in the pipeline (BASELINE.md "host input-path
bound"): rasterizing two 512x512 maps per sample dominates the per-sample
augmentation budget even with the native C++ kernels.

On TPU the same math is a handful of fused elementwise ops over a static
512x512 grid — effectively free next to the forward pass.  This module is the
jittable twin of :mod:`..data.guidance`:

* :func:`extreme_points_random` / :func:`extreme_points_fixed` — the 4
  extreme pixels of a binary mask, random-tie vs deterministic-median
  selection, matching the host contracts (``data/guidance.py:56,72``);
* :func:`guidance_map` — one (H, W) guidance channel from a mask, any of the
  three point-based families (``nellipse_gaussians`` — the live channel —
  ``nellipse``, ``extreme_points``), numerically matching the host maps;
* :func:`make_device_guidance` — the ``(batch, rng) -> batch`` stage for
  ``ops.augment.make_device_augment(guidance_fn=...)``: computes the channel
  from ``crop_gt`` AFTER the device geometric augmentations (the reference's
  stage order: geometry happens before guidance, train_pascal.py:123-134) and
  appends it to ``concat``.

Randomness note: the live path samples extreme points with ``pert=0`` — the
jitter is the uniform choice among each side's tied extreme pixels.  The host
picks a uniform index into the candidate list; here the same distribution is
realized as an argmax over iid uniforms (a different RNG stream, identical
law).  The deterministic (val) variant is bit-exact vs the host at ``pert=0``,
where each side's candidates have unique sort keys.

The confidence-map families (``confidence_l1l2``/``confidence_gaussian``,
the reference's inactive alternative at custom_transforms.py:253-298) are
covered too: mask moments are masked sums over the static grid and the 2x2
covariance/axes inverses are closed-form — no linear-algebra escape hatch
needed.
"""

from __future__ import annotations

from typing import Callable, Mapping

import jax
import jax.numpy as jnp

Batch = Mapping[str, jax.Array]

#: families this module can synthesize on device
FAMILIES = ("nellipse_gaussians", "nellipse", "extreme_points",
            "confidence_l1l2", "confidence_gaussian")

# Plain python int, NOT jnp.int32(...): a module-level jnp call executes a
# primitive at import time, which initializes the default backend — on a
# tunneled-TPU host that can block every `import distributedpytorch_tpu`
# for minutes when the tunnel is unhealthy (observed via faulthandler).
# Inside the jitted functions the weak int promotes to int32 as before.
_BIG = 1 << 30


def _side_candidates(mask: jax.Array, pert: int):
    """Boolean candidate maps for (left, top, right, bottom) — foreground
    pixels within ``pert`` px of each side's extreme coordinate (the host
    ``_extreme_point_candidates`` contract, data/guidance.py:41)."""
    fg = mask > 0.5
    h, w = mask.shape
    x = jnp.arange(w, dtype=jnp.int32)[None, :]
    y = jnp.arange(h, dtype=jnp.int32)[:, None]
    xmin = jnp.min(jnp.where(fg, x, _BIG))
    ymin = jnp.min(jnp.where(fg, y, _BIG))
    xmax = jnp.max(jnp.where(fg, x, -1))
    ymax = jnp.max(jnp.where(fg, y, -1))
    return (
        fg & (jnp.abs(x - xmin) <= pert),
        fg & (jnp.abs(y - ymin) <= pert),
        fg & (jnp.abs(x - xmax) <= pert),
        fg & (jnp.abs(y - ymax) <= pert),
    )


def extreme_points_random(mask: jax.Array, rng: jax.Array,
                          pert: int = 0) -> jax.Array:
    """Randomized 4 extreme points of ``mask`` as a (4, 2) float32 (x, y)
    array — uniform over each side's candidate set, the training-time jitter
    of the host ``extreme_points`` (data/guidance.py:56).

    Selection is the host's own ``k = integers(0, n_candidates)`` realized
    as a cumsum rank-pick — 4 random ints per sample, not a random field
    per side (threefry over the full grid would cost more than the map
    rasterization itself).

    Undefined (but finite) for an empty mask; callers zero the resulting map.
    """
    h, w = mask.shape
    cands = jnp.stack([c.ravel()
                       for c in _side_candidates(mask, pert)])  # (4, H*W)
    counts = cands.sum(axis=1)
    ks = jax.random.randint(rng, (4,), 0, jnp.maximum(counts, 1))
    # the first flat index whose candidate-cumsum reaches k+1 IS the k-th
    # candidate in row-major order
    csum = jnp.cumsum(cands, axis=1)
    idx = jnp.argmax(csum == (ks + 1)[:, None], axis=1)
    return jnp.stack([idx % w, idx // w], axis=1).astype(jnp.float32)


def extreme_points_fixed(mask: jax.Array, pert: int = 0) -> jax.Array:
    """Deterministic 4 extreme points — per side, the candidate of median
    rank when ordered by the non-extreme coordinate (the host
    ``extreme_points_fixed`` contract, data/guidance.py:72; ties — possible
    only at ``pert > 0`` — break by row-major position where the host's
    unstable sort is unspecified).  (4, 2) float32 (x, y)."""
    h, w = mask.shape
    x = jnp.arange(w, dtype=jnp.int32)[None, :]
    y = jnp.arange(h, dtype=jnp.int32)[:, None]
    # sort keys: (other coordinate, tie-break) packed into one int32
    key_lr = y * w + x          # left/right sides: other = y -> (y, x) order
    key_tb = x * h + y          # top/bottom sides: other = x -> (x, y) order
    pts = []
    for i, cand in enumerate(_side_candidates(mask, pert)):
        keys = jnp.where(cand, key_lr if i in (0, 2) else key_tb, _BIG)
        sel = jnp.sort(keys.ravel())[jnp.sum(cand) // 2]
        if i in (0, 2):
            pts.append((sel % w, sel // w))
        else:
            pts.append((sel // h, sel % h))
    return jnp.stack([jnp.stack(p) for p in pts]).astype(jnp.float32)


def _nellipse_z(shape_hw, pts: jax.Array, softness: float) -> jax.Array:
    """Soft n-ellipse indicator in [0, 1] — jittable twin of the host
    ``compute_nellipse`` (data/guidance.py:99): boundary at the multifocal
    level set through the outermost focal point, sigmoid falloff of relative
    width ``softness``, exponent clipped to +-50."""
    h, w = shape_hw
    xx = jnp.arange(w, dtype=jnp.float32)[None, :]
    yy = jnp.arange(h, dtype=jnp.float32)[:, None]
    px = pts[:, 0][:, None, None]
    py = pts[:, 1][:, None, None]
    d = jnp.sqrt((xx - px) ** 2 + (yy - py) ** 2).sum(axis=0)
    pair = jnp.sqrt(((pts[:, None, :] - pts[None, :, :]) ** 2).sum(-1))
    c = pair.sum(axis=1).max()
    tau = jnp.where(c > 0, softness * c, 1.0)
    z = 1.0 / (1.0 + jnp.exp(jnp.clip((d - c) / tau, -50.0, 50.0)))
    return jnp.where(c > 0, z, (d == 0).astype(jnp.float32))


def _gaussian_hm(shape_hw, pts: jax.Array, sigma: float) -> jax.Array:
    """Max-combined gaussian bumps at ``pts`` in [0, 1] — twin of the host
    ``make_gt`` (utils/helpers.py:252: exp(-4 ln2 r^2 / sigma^2))."""
    h, w = shape_hw
    xx = jnp.arange(w, dtype=jnp.float32)[None, :]
    yy = jnp.arange(h, dtype=jnp.float32)[:, None]
    px = pts[:, 0][:, None, None]
    py = pts[:, 1][:, None, None]
    r2 = (xx - px) ** 2 + (yy - py) ** 2
    return jnp.exp(-4.0 * jnp.log(2.0) * r2 / sigma**2).max(axis=0)


def _inv2x2(m: jax.Array) -> jax.Array:
    """Closed-form inverse of a 2x2 matrix."""
    a, b, c, d = m[0, 0], m[0, 1], m[1, 0], m[1, 1]
    det = a * d - b * c
    return jnp.array([[d, -b], [-c, a]]) / det


def _minmax_255(z: jax.Array) -> jax.Array:
    """Min-max normalize to [0, 1] then x255 — the host
    ``normalize_wt_map(.)*255`` rule (transforms.AddConfidenceMap)."""
    lo, hi = z.min(), z.max()
    return (z - lo) / (hi - lo + 1e-10) * 255.0


def _l1l2_map(shape_hw, pts: jax.Array, tau: float) -> jax.Array:
    """Skewed-axes L1+L2 confidence map — twin of the host
    ``generate_mv_l1l2_image_skewed_axes`` (data/guidance.py:248): affine
    (u, v) coordinates along the left->right / top->bottom chords, weight
    ``exp(-tau * (|u|+|v| + sqrt(u^2+v^2)) / 2)``."""
    h, w = shape_hw
    left, top, right, bottom = pts[0], pts[1], pts[2], pts[3]
    center = pts.mean(axis=0)
    a1 = (right - left) / 2.0
    a2 = (bottom - top) / 2.0
    A = jnp.stack([a1, a2], axis=1)  # columns are the axes
    A = jnp.where(jnp.abs(A[0, 0] * A[1, 1] - A[0, 1] * A[1, 0]) < 1e-6,
                  A + jnp.eye(2) * 1e-3, A)
    Ainv = _inv2x2(A)
    xx = jnp.arange(w, dtype=jnp.float32)[None, :]
    yy = jnp.arange(h, dtype=jnp.float32)[:, None]
    dx = xx - center[0]
    dy = yy - center[1]
    u = Ainv[0, 0] * dx + Ainv[0, 1] * dy
    v = Ainv[1, 0] * dx + Ainv[1, 1] * dy
    l1 = jnp.abs(u) + jnp.abs(v)
    l2 = jnp.sqrt(u * u + v * v)
    return jnp.exp(-tau * (l1 + l2) / 2.0)


def _mvgauss_map(mask: jax.Array, tau: float) -> jax.Array:
    """Multivariate-gaussian confidence map from the mask's pixel-cloud
    moments — twin of the host ``generate_mvgauss_image``
    (data/guidance.py:218).  Moments are masked sums over the static grid;
    covariance is the sample (ddof=1) covariance + 1e-3*I, isotropic unit
    for sub-2-pixel masks."""
    h, w = mask.shape
    fg = (mask > 0.5).astype(jnp.float32)
    n = fg.sum()
    xx = jnp.arange(w, dtype=jnp.float32)[None, :] * jnp.ones((h, 1))
    yy = jnp.arange(h, dtype=jnp.float32)[:, None] * jnp.ones((1, w))
    n_safe = jnp.maximum(n, 1.0)
    mx = (fg * xx).sum() / n_safe
    my = (fg * yy).sum() / n_safe
    dof = jnp.maximum(n - 1.0, 1.0)
    sxx = (fg * (xx - mx) ** 2).sum() / dof
    syy = (fg * (yy - my) ** 2).sum() / dof
    sxy = (fg * (xx - mx) * (yy - my)).sum() / dof
    cov = jnp.array([[sxx, sxy], [sxy, syy]]) + jnp.eye(2) * 1e-3
    cov = jnp.where(n < 2.0, jnp.eye(2), cov)
    icov = _inv2x2(cov)
    dx = xx - mx
    dy = yy - my
    m = (icov[0, 0] * dx * dx + (icov[0, 1] + icov[1, 0]) * dx * dy
         + icov[1, 1] * dy * dy)
    return jnp.exp(-0.5 * tau * m)


def guidance_map(
    mask: jax.Array,
    rng: jax.Array | None = None,
    family: str = "nellipse_gaussians",
    alpha: float = 0.6,
    sigma: float = 10.0,
    softness: float = 0.05,
    pert: int = 0,
    is_val: bool = False,
    tau: float = 1.0,
) -> jax.Array:
    """One (H, W) float32 guidance channel from a binary mask.

    Families and their scaling mirror the host transforms exactly:
    ``nellipse_gaussians`` — z1 + alpha*z2 rescaled to peak 255 (the live
    channel, transforms.NEllipseWithGaussians); ``nellipse`` — indicator x255;
    ``extreme_points`` — unscaled [0, 1] heatmap; ``confidence_l1l2`` /
    ``confidence_gaussian`` — min-max-normalized x255 (AddConfidenceMap,
    whose gaussian branch pins tau=0.5).  Degenerate masks zero the map:
    empty for the point families, empty-or-full for the confidence families
    (the host's ``len(np.unique(mask)) == 1`` rule).
    """
    if family not in FAMILIES:
        raise ValueError(f"family {family!r} not device-supported {FAMILIES}")
    shape = mask.shape
    if family == "confidence_gaussian":
        pts = None  # moments-only family
    elif is_val:
        pts = extreme_points_fixed(mask, pert)
    else:
        if rng is None:
            raise ValueError("training-mode guidance_map needs an rng")
        pts = extreme_points_random(mask, rng, pert)
    if family == "extreme_points":
        z = _gaussian_hm(shape, pts, sigma)
    elif family == "nellipse":
        z = _nellipse_z(shape, pts, softness) * 255.0
    elif family == "confidence_l1l2":
        z = _minmax_255(_l1l2_map(shape, pts, tau))
    elif family == "confidence_gaussian":
        z = _minmax_255(_mvgauss_map(mask, 0.5))
    else:
        z1 = _nellipse_z(shape, pts, softness)
        z2 = _gaussian_hm(shape, pts, sigma)
        z = z1 * 255.0 + z2 * (255.0 * alpha)
        z = jnp.clip(z * (255.0 / jnp.maximum(z.max(), 1e-12)), 0.0, 255.0)
    live = jnp.any(mask > 0.5)
    if family.startswith("confidence"):
        live = live & jnp.any(mask <= 0.5)
    return jnp.where(live, z, 0.0).astype(jnp.float32)


def make_device_guidance(
    family: str = "nellipse_gaussians",
    alpha: float = 0.6,
    sigma: float = 10.0,
    softness: float = 0.05,
    pert: int | None = None,
    is_val: bool = False,
    tau: float = 1.0,
) -> Callable[[Batch, jax.Array], dict]:
    """Build the ``(batch, rng) -> batch`` stage appending the guidance
    channel to ``concat`` from ``crop_gt``, per sample.

    ``pert=None`` picks each family's pipeline default
    (pipeline._guidance_stage: ``extreme_points`` and the confidence
    families train with 5 px of point jitter; the n-ellipse families use 0).
    Feed the host pipeline ``guidance='none'`` so ``concat`` arrives with
    the bare image channels.
    """
    if family not in FAMILIES:
        raise ValueError(f"family {family!r} not device-supported {FAMILIES}")
    if pert is None:
        jittered = family in ("extreme_points", "confidence_l1l2",
                              "confidence_gaussian")
        pert = 5 if (jittered and not is_val) else 0

    def stage(batch: Batch, rng: jax.Array) -> dict:
        x = batch["concat"]
        gt = batch["crop_gt"]
        gt2 = gt[..., 0] if gt.ndim == 4 else gt
        keys = jax.random.split(rng, x.shape[0])

        def one(mask, key):
            return guidance_map(mask, key, family=family, alpha=alpha,
                                sigma=sigma, softness=softness, pert=pert,
                                is_val=is_val, tau=tau)

        maps = jax.vmap(one)(gt2, keys)
        out = dict(batch)
        out["concat"] = jnp.concatenate(
            [x, maps[..., None].astype(x.dtype)], axis=-1)
        return out

    return stage
