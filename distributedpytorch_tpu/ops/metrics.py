"""Evaluation metrics: void-aware Jaccard (IoU) with threshold sweep.

The reference's quality metric (its ``calc_jaccard`` from the missing
``dataloaders.implementation`` module): per-sample IoU of the binarized
prediction vs ground truth, excluding void pixels, evaluated at thresholds
{0.3, 0.5, 0.8} with the best-threshold mean gating checkpoint saves
(reference train_pascal.py:281,291,298-304).

Two forms:

* device-side (:func:`jaccard`, :func:`batched_jaccard`,
  :func:`threshold_sweep_jaccard`) — jnp, fixed shapes, usable inside a jitted
  eval step on crop-space predictions;
* the full-resolution paste-back protocol (crop -> original image coords via
  ``utils.helpers.crop2fullmask``) is ragged-shape and stays host-side in the
  evaluator (``train.evaluate``), mirroring where the reference ran it (CPU,
  train_pascal.py:283-291).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

#: the reference's eval threshold sweep (train_pascal.py:281)
DEFAULT_THRESHOLDS = (0.3, 0.5, 0.8)


def jaccard(
    pred: jax.Array, gt: jax.Array, void: jax.Array | None = None
) -> jax.Array:
    """IoU of two binary masks, excluding void pixels.  Empty-union -> 1.0
    (an empty prediction of an empty ground truth is a perfect match)."""
    pred = pred.astype(jnp.bool_)
    gt = gt.astype(jnp.bool_)
    valid = (
        jnp.ones_like(gt) if void is None else jnp.logical_not(void.astype(jnp.bool_))
    )
    inter = jnp.sum(pred & gt & valid)
    union = jnp.sum((pred | gt) & valid)
    return jnp.where(union == 0, 1.0, inter / jnp.maximum(union, 1))


def batched_jaccard(
    pred: jax.Array, gt: jax.Array, void: jax.Array | None = None
) -> jax.Array:
    """Per-sample IoU over a leading batch axis: (B, ...) -> (B,)."""
    fn = jax.vmap(lambda p, g, v: jaccard(p, g, v))
    if void is None:
        void = jnp.zeros_like(gt)
    return fn(pred, gt, void)


def threshold_sweep_jaccard(
    probs: jax.Array,
    gt: jax.Array,
    void: jax.Array | None = None,
    thresholds: tuple[float, ...] = DEFAULT_THRESHOLDS,
) -> jax.Array:
    """IoU of ``probs > t`` for each threshold: (B, ...) -> (T, B)."""
    return jnp.stack(
        [batched_jaccard(probs > t, gt, void) for t in thresholds]
    )


def np_jaccard(pred: np.ndarray, gt: np.ndarray, void: np.ndarray | None = None) -> float:
    """Host-side (numpy) twin of :func:`jaccard` for the ragged full-res
    paste-back path — per-image sizes vary so this cannot be batched/jitted."""
    pred = pred.astype(bool)
    gt = gt.astype(bool)
    valid = np.ones_like(gt) if void is None else ~void.astype(bool)
    inter = int(np.sum(pred & gt & valid))
    union = int(np.sum((pred | gt) & valid))
    return 1.0 if union == 0 else inter / union


def np_jaccard_thresholds(
    prob: np.ndarray,
    thresholds,
    gt: np.ndarray,
    void: np.ndarray | None = None,
) -> np.ndarray:
    """Threshold-swept IoU in ONE pass over the image.

    The reference protocol scores ``prob > t`` for each t in {0.3, 0.5,
    0.8} (train_pascal.py:281-291); the naive form walks the full-res
    image once per threshold.  Digitizing ``prob`` against the sorted
    thresholds instead gives every threshold's intersection/union from two
    bin-counts via suffix sums — the host paste-back loop's scoring cost
    stops scaling with ``len(thresholds)``.

    Exact equality semantics match ``prob > t`` (strict): bin index k
    counts thresholds strictly below the value, so a pixel AT a threshold
    is not predicted positive for it.  Returns IoUs in the CALLER'S
    threshold order.
    """
    prob = np.asarray(prob)
    # thresholds must compare in PROB's dtype: ``prob > 0.3`` on float32
    # casts the scalar to float32 (0.3f != 0.3), so a float64 threshold
    # table here would flip at-threshold pixels relative to the naive form
    t = np.asarray(thresholds, dtype=prob.dtype if
                   np.issubdtype(prob.dtype, np.floating) else np.float64)
    order = np.argsort(t, kind="stable")
    ts = t[order]
    k = ts.size
    gt = gt.astype(bool).ravel()
    valid = np.ones_like(gt) if void is None \
        else ~np.asarray(void).astype(bool).ravel()
    # searchsorted 'left': #(ts < x); pred for threshold j  <=>  bin > j
    bins = np.searchsorted(ts, prob.ravel(), side="left")
    gt_counts = np.bincount(bins[gt & valid], minlength=k + 1)
    ngt_counts = np.bincount(bins[~gt & valid], minlength=k + 1)
    # suffix sums over bins j+1..k = counts where pred_j is True
    inter = np.cumsum(gt_counts[::-1])[::-1]        # inter[j+1..] summed
    pred_only = np.cumsum(ngt_counts[::-1])[::-1]
    n_gt_valid = int(gt_counts.sum())
    out = np.empty(k)
    for j in range(k):
        i = int(inter[j + 1])
        u = n_gt_valid + int(pred_only[j + 1])
        out[j] = 1.0 if u == 0 else i / u
    inv = np.empty(k, np.intp)
    inv[order] = np.arange(k)
    return out[inv]


# ---------------------------------------------------------------------------
# multi-class semantic metrics (the DeepLabV3 "val mIoU" of BASELINE.md)
# ---------------------------------------------------------------------------

def confusion_matrix(
    pred: jax.Array, label: jax.Array, nclass: int, ignore_index: int = 255
) -> jax.Array:
    """(C, C) confusion counts, rows = true class, cols = predicted class.

    ``pred``/``label``: int arrays of any (equal) shape; ``ignore_index``
    pixels are dropped (the in-band void convention of the semantic
    pipeline).  Jit-safe: one bincount over ``true * C + pred``.
    """
    pred = pred.reshape(-1).astype(jnp.int32)
    label = label.reshape(-1).astype(jnp.int32)
    valid = label != ignore_index
    idx = jnp.where(valid, label * nclass + pred, nclass * nclass)
    counts = jnp.bincount(idx, length=nclass * nclass + 1)[:-1]
    return counts.reshape(nclass, nclass)


def miou_from_confusion(conf) -> dict:
    """Per-class IoU / mean IoU / pixel accuracy from a (C, C) confusion.

    Classes absent from both prediction and ground truth (union == 0) are
    excluded from the mean, the standard VOC convention.
    """
    conf = np.asarray(conf, dtype=np.float64)
    inter = np.diag(conf)
    union = conf.sum(0) + conf.sum(1) - inter
    with np.errstate(divide="ignore", invalid="ignore"):
        iou = np.where(union > 0, inter / union, np.nan)
    miou = float(np.nanmean(iou)) if np.any(union > 0) else 0.0
    total = conf.sum()
    return {
        "miou": miou,
        "per_class_iou": [None if np.isnan(v) else float(v) for v in iou],
        "pixel_acc": float(inter.sum() / total) if total > 0 else 0.0,
    }
