"""Segmentation losses.

Replaces the reference's external ``SegmentationMultiLosses`` (imported from a
missing ``layers.loss_weighted`` module at reference train_pascal.py:33 and
applied to the DANet 3-tuple output at train_pascal.py:119,199 — the
"wtd_loss" in its best-checkpoint filename, train_pascal.py:304).  All losses
are pure functions of logits — the sigmoid at reference train_pascal.py:262,284
lives in eval/vis code only, so training is from-logits and XLA fuses the
log-sum-exp into the preceding conv.

Void-pixel semantics: the reference zeroes 255-labeled pixels out of the
target (pascal.py:240-242) and excludes them from the metric
(train_pascal.py:291); here the loss itself also masks them, the from-logits
equivalent of ``ignore_index=255``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sigmoid_balanced_bce(
    logits: jax.Array,
    labels: jax.Array,
    void: jax.Array | None = None,
    balanced: bool = True,
) -> jax.Array:
    """Class-balanced binary cross-entropy from logits, void-aware.

    ``logits``/``labels``: (..., H, W[, 1]) broadcast-compatible; ``labels``
    binary {0,1}.  With ``balanced=True`` positive/negative pixels are
    reweighted by the opposite class's frequency (computed over valid pixels
    only) — the standard interactive-segmentation balancing for the extreme
    foreground/background skew of single-instance masks.  Returns a scalar.
    """
    logits = logits.astype(jnp.float32)
    labels = labels.astype(jnp.float32)
    valid = jnp.ones_like(labels) if void is None else (1.0 - void.astype(jnp.float32))
    # Stable BCE from logits: max(x,0) - x*z + log1p(exp(-|x|))
    per_pix = (
        jnp.maximum(logits, 0.0)
        - logits * labels
        + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    )
    if balanced:
        n_valid = valid.sum()
        n_pos = (labels * valid).sum()
        w_pos = 1.0 - n_pos / jnp.maximum(n_valid, 1.0)
        weights = jnp.where(labels > 0.5, w_pos, 1.0 - w_pos) * valid
    else:
        weights = valid
    return (per_pix * weights).sum() / jnp.maximum(valid.sum(), 1.0)


def multi_output_loss(
    outputs: tuple[jax.Array, ...],
    labels: jax.Array,
    void: jax.Array | None = None,
    weights: tuple[float, ...] | None = None,
    balanced: bool = True,
) -> jax.Array:
    """Weighted sum of per-output losses over a multi-head model output.

    The ``SegmentationMultiLosses`` contract: the DANet head emits
    (fused, position-attention, channel-attention) predictions and all three
    are supervised against the same target (reference train_pascal.py:119,199).
    ``weights`` defaults to all-ones.
    """
    if weights is None:
        weights = (1.0,) * len(outputs)
    total = jnp.float32(0.0)
    for out, w in zip(outputs, weights):
        total = total + w * sigmoid_balanced_bce(out, labels, void, balanced)
    return total


def se_presence_loss(
    logits: jax.Array,
    labels: jax.Array,
    ignore_index: int = 255,
) -> jax.Array:
    """Semantic-encoding (SE) loss: per-image class-presence BCE.

    The EncNet training objective's auxiliary term (Zhang et al. CVPR'18,
    the PyTorch-Encoding package the reference pulls its models from —
    reference train_pascal.py:32): the context-encoding branch predicts
    which classes appear anywhere in the image, forcing the encoded global
    descriptor to carry scene-level semantics.  ``logits``: (B, C);
    ``labels``: int (B, H, W) with ``ignore_index`` void pixels excluded
    from the presence derivation.  Returns the mean BCE over (B, C).
    """
    c = logits.shape[-1]
    flat = labels.reshape(labels.shape[0], -1)
    valid = flat != ignore_index
    # presence[b, k] = any valid pixel of class k; the (B, N, C) compare
    # feeds straight into the any-reduce — XLA fuses it, nothing N*C-sized
    # is materialized.
    present = jnp.any(
        (flat[..., None] == jnp.arange(c)) & valid[..., None], axis=1
    ).astype(jnp.float32)
    x = logits.astype(jnp.float32)
    per = jnp.maximum(x, 0.0) - x * present + jnp.log1p(jnp.exp(-jnp.abs(x)))
    return per.mean()


def softmax_xent_ignore(
    logits: jax.Array,
    labels: jax.Array,
    ignore_index: int = 255,
) -> jax.Array:
    """Multi-class softmax cross-entropy with ``ignore_index`` semantics.

    ``logits``: (..., C); ``labels``: int (...) with ``ignore_index`` marking
    void pixels (the reference's 255-labeled boundary pixels,
    pascal.py:240-242).  Ignored pixels contribute zero and are excluded
    from the mean — the multi-class loss for the DeepLabV3 semantic-
    segmentation configs of BASELINE.md.

    The label log-prob is selected with a compare-select-reduce over the
    class axis rather than ``take_along_axis``: XLA lowers the gather to a
    scalar per-element loop on TPU (measured 1.6 GiB/s, 28.9 ms per head at
    8x513x513x21 — 60% of the whole DeepLabV3 step, r4 profile
    ``prof_deeplab_b8.json``), while the select fuses into the surrounding
    elementwise work.  ``where`` (not one_hot multiply) keeps non-selected
    lanes exactly zero even for non-finite logits.
    """
    valid = (labels != ignore_index)
    safe_labels = jnp.where(valid, labels, 0)
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    klass = jax.lax.broadcasted_iota(
        safe_labels.dtype, logits.shape, logits.ndim - 1)
    gold = jnp.where(
        klass == safe_labels[..., None], logits, jnp.float32(0.0)
    ).sum(axis=-1)
    per_pix = (logz - gold) * valid
    return per_pix.sum() / jnp.maximum(valid.sum(), 1)
