"""Attention primitives for the dual-attention segmentation head.

The reference's DANet model (imported from PyTorch-Encoding at reference
train_pascal.py:32,86) pairs a *position* attention module (full self-attention
over the H/8 x W/8 spatial tokens) with a *channel* attention module (gram-matrix
attention over feature channels).  Those live in external CUDA code there; here
they are pure jnp functions the flax modules call, designed for the MXU:

* everything is batched einsum — XLA tiles these straight onto the systolic
  array; no python loops over tokens;
* :func:`blocked_position_attention` is the same math with an online-softmax
  scan over key/value blocks, so the N x N score matrix is never materialized.
  This is the memory-bound form that scales to long token counts and is the
  building block the ring/sequence-parallel path reuses (each ring hop feeds
  one key/value block and carries the same running (max, sum, acc) state).

Layouts: spatial features are (B, N, C) token-major — N = H*W spatial tokens —
the natural NHWC flattening.  Scores accumulate in float32 regardless of input
dtype (bf16-safe softmax).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def position_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                       score_dtype: jnp.dtype | None = None) -> jax.Array:
    """Full position (spatial self-) attention.

    ``q``/``k``: (B, N, Ck), ``v``: (B, N, Cv) -> (B, N, Cv).

    Semantics of the reference DANet position-attention module (consumed via
    the 3-tuple output indexed at reference train_pascal.py:258-260): raw
    dot-product scores over all token pairs, softmax over keys, no scaling
    term — DANet uses unscaled energies with a learned residual gate (the
    gate lives in the calling flax module).

    ``score_dtype`` controls the dtype the N x N score matrix is
    *materialized* in between the einsum and the softmax — the single
    largest HBM tenant of the whole step at big crops (4096 tokens: 64 MB
    in f32, written once and re-read by the softmax's reduce+exp passes).
    ``bfloat16`` halves that traffic.  Numerics stay conservative either
    way: the einsum always *accumulates* in f32 (rounded only on store)
    and the softmax arithmetic (max, exp, sum, div) always runs in f32 —
    XLA fuses the up/downcasts into the neighboring kernels, so the only
    cost is one bf16 rounding of the raw scores and none of the reductions
    lose precision.  The attention-weight matrix itself already
    materializes in ``v.dtype`` (bf16 under mixed precision) regardless.
    ``None`` keeps the f32 materialization.
    """
    scores = jnp.einsum("bnc,bmc->bnm", q, k, preferred_element_type=jnp.float32)
    if score_dtype is not None:
        scores = scores.astype(score_dtype)
    attn = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(v.dtype)
    return jnp.einsum("bnm,bmc->bnc", attn, v)


def blocked_position_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, block_size: int = 1024
) -> jax.Array:
    """Position attention with online softmax over key/value blocks.

    Identical math to :func:`position_attention` but O(N * block) memory: a
    ``lax.scan`` over K/V blocks carries running (row-max, row-sum, weighted
    accumulator) state — the flash-attention recurrence.  Use when N*N scores
    would not fit HBM (large crops / long sequences); also the per-hop kernel
    of the ring-attention path (parallel.ring).
    """
    b, n, ck = q.shape
    cv = v.shape[-1]
    nb = -(-n // block_size)  # ceil
    pad = nb * block_size - n
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0)))
    kb = k.reshape(b, nb, block_size, ck)
    vb = v.reshape(b, nb, block_size, cv)
    # Mask padded keys with -inf scores so they never receive weight.
    key_valid = (jnp.arange(nb * block_size) < n).reshape(nb, block_size)

    def step(carry, blk):
        m, s, acc = carry  # (B,N) running max, (B,N) running sum, (B,N,Cv)
        kblk, vblk, valid = blk
        scores = jnp.einsum(
            "bnc,bmc->bnm", q, kblk, preferred_element_type=jnp.float32
        )
        scores = jnp.where(valid[None, None, :], scores, -jnp.inf)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        # Rescale previous accumulator to the new max; exp(-inf - m) == 0
        # handles the first block / fully-masked rows without special cases.
        correction = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new[..., None])
        s_new = s * correction + p.sum(axis=-1)
        acc_new = acc * correction[..., None] + jnp.einsum(
            "bnm,bmc->bnc", p, vblk.astype(jnp.float32)
        )
        return (m_new, s_new, acc_new), None

    init = (
        jnp.full((b, n), -jnp.inf, jnp.float32),
        jnp.zeros((b, n), jnp.float32),
        jnp.zeros((b, n, cv), jnp.float32),
    )
    (m, s, acc), _ = jax.lax.scan(
        step,
        init,
        (kb.swapaxes(0, 1), vb.swapaxes(0, 1), key_valid),
    )
    return (acc / s[..., None]).astype(v.dtype)


def channel_attention(x: jax.Array) -> jax.Array:
    """Channel (gram-matrix) attention: (B, N, C) -> (B, N, C).

    Semantics of the reference DANet channel-attention module (its map is the
    4th visualization panel at reference train_pascal.py:260,274-275): the
    C x C channel-affinity gram matrix, passed through the max-subtraction
    trick (affinity' = rowmax - affinity) before softmax — attending to the
    *least* similar channels, which is DANet's published CAM formulation —
    then applied back over channels.  No projections; the learned residual
    gate lives in the calling module.
    """
    xf = x.astype(jnp.float32)
    energy = jnp.einsum("bni,bnj->bij", xf, xf)  # (B, C, C)
    energy = energy.max(axis=-1, keepdims=True) - energy
    attn = jax.nn.softmax(energy, axis=-1)
    out = jnp.einsum("bij,bnj->bni", attn, xf)
    return out.astype(x.dtype)
