"""Pallas TPU flash-attention kernel for the position-attention hot path.

The reference's position-attention module materializes the full
(H·W/64)² score matrix in external CUDA code (PyTorch-Encoding's DANet head,
reference train_pascal.py:32,86).  :func:`ops.attention.position_attention`
is the XLA einsum re-expression; this module is the hand-scheduled form for
when the fused-by-XLA version is memory- or bandwidth-bound: one kernel
computes Q·Kᵀ on the MXU, the online softmax on the VPU, and the P·V matmul
on the MXU per (Q-block, K-block) tile, keeping everything in VMEM and never
writing an N×N intermediate to HBM.

Grid layout: ``(batch, q_blocks, k_blocks)`` with the K dimension innermost;
the running (max, sum, accumulator) state lives in VMEM scratch that persists
across the K sweep for each Q block (the canonical flash-attention TPU
schedule).  Block sizes default to 256×256, aligned to the (8,128) f32 tile.

Backward: a ``jax.custom_vjp`` whose reverse pass recomputes attention with
:func:`ops.attention.blocked_position_attention` (O(N·block) memory) and
differentiates that — recompute-not-store, the standard flash trade.

Tests run this kernel with ``interpret=True`` on CPU (pallas's interpreter
executes the same program the Mosaic compiler lowers on TPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import blocked_position_attention

_NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, s_ref, acc_ref,
                  *, n_real: int, block_k: int, scale: float | None):
    """One (q-block, k-block) tile of online-softmax attention."""
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        s_ref[:] = jnp.zeros_like(s_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0]          # (bq, ck)
    k = k_ref[0]          # (bk, ck)
    v = v_ref[0]          # (bk, cv)
    scores = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)          # (bq, bk)
    if scale is not None:
        scores = scores * scale
    # Mask keys past the true token count (N was padded to a block multiple).
    key_idx = j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, scores.shape, 1)
    scores = jnp.where(key_idx < n_real, scores, _NEG_INF)

    m_prev = m_ref[:, :1]                            # (bq, 1)
    m_new = jnp.maximum(m_prev, scores.max(axis=-1, keepdims=True))
    corr = jnp.exp(m_prev - m_new)
    p = jnp.exp(scores - m_new)                      # (bq, bk)
    s_new = s_ref[:, :1] * corr + p.sum(axis=-1, keepdims=True)
    acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    s_ref[:] = jnp.broadcast_to(s_new, s_ref.shape)

    @pl.when(j == nk - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:] / jnp.maximum(s_ref[:, :1], 1e-30)
                    ).astype(o_ref.dtype)


def _flash_forward(q, k, v, block_q: int, block_k: int,
                   scale: float | None, interpret: bool | None):
    if interpret is None:
        # Mosaic compiles on TPU; everywhere else run the same program in
        # the pallas interpreter (slow but correct — CI / CPU meshes).
        interpret = jax.default_backend() != "tpu"
    b, n, ck = q.shape
    cv = v.shape[-1]
    nq = pl.cdiv(n, block_q)
    nk = pl.cdiv(n, block_k)
    pad_q = nq * block_q - n
    pad_k = nk * block_k - n
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0)))

    kernel = functools.partial(_flash_kernel, n_real=n, block_k=block_k,
                               scale=scale)
    out = pl.pallas_call(
        kernel,
        grid=(b, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, ck), lambda b_, i, j: (b_, i, 0)),
            pl.BlockSpec((1, block_k, ck), lambda b_, i, j: (b_, j, 0)),
            pl.BlockSpec((1, block_k, cv), lambda b_, i, j: (b_, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, cv), lambda b_, i, j: (b_, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, nq * block_q, cv), v.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),   # running max
            pltpu.VMEM((block_q, 128), jnp.float32),   # running sum
            pltpu.VMEM((block_q, cv), jnp.float32),    # accumulator
        ],
        interpret=interpret,
    )(q, k, v)
    return out[:, :n, :]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_position_attention(q, k, v, block_q: int = 256, block_k: int = 256,
                             scale: float | None = None,
                             interpret: bool | None = None):
    """Flash position attention: same math as
    :func:`ops.attention.position_attention` (unscaled DANet energies unless
    ``scale``), O(N·block) memory, MXU-scheduled.

    ``q``/``k``: (B, N, Ck); ``v``: (B, N, Cv) -> (B, N, Cv).
    """
    return _flash_forward(q, k, v, block_q, block_k, scale, interpret)


def _fwd(q, k, v, block_q, block_k, scale, interpret):
    out = _flash_forward(q, k, v, block_q, block_k, scale, interpret)
    return out, (q, k, v)


def _bwd(block_q, block_k, scale, interpret, res, g):
    q, k, v = res
    # Recompute with the O(N*block) jnp form and differentiate that — the
    # flash backward without a second hand-written kernel.
    def ref(q_, k_, v_):
        if scale is not None:  # score scaling == scaling q
            q_ = q_ * scale
        return blocked_position_attention(q_, k_, v_, block_size=block_k)
    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(g)


flash_position_attention.defvjp(_fwd, _bwd)
