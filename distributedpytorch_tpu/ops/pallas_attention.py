"""Pallas TPU kernels for BOTH DANet attention branches — the hot path.

The reference's dual-attention head materializes its intermediates in
external CUDA code (PyTorch-Encoding's DANet head, reference
train_pascal.py:32,86): the (H·W/64)² position-attention score matrix and
the C×C channel gram matrix.  :mod:`ops.attention` is the XLA einsum
re-expression; this module is the hand-scheduled TPU form — the default
hot path on TPU (``model.attention_impl=auto``), with the XLA forms as
the off-TPU fallback:

* :func:`flash_position_attention` — one kernel computes Q·Kᵀ on the
  MXU, the online softmax on the VPU, and the P·V matmul on the MXU per
  (Q-block, K-block) tile, keeping everything in VMEM and never writing
  an N×N intermediate to HBM.  Grid ``(batch, q_blocks, k_blocks)``,
  K innermost; the running (max, sum, accumulator) state lives in VMEM
  scratch across the K sweep (the canonical flash-attention schedule).
  Blocks default 256×256, aligned to the (8,128) f32 tile.
* :func:`flash_channel_attention` — the gram branch: one kernel streams
  the (N, C) tokens through VMEM in row blocks, accumulates the C×C
  gram on the MXU in VMEM scratch and finishes with DANet's
  max-subtraction softmax on the VPU *in the same kernel* (the energy
  matrix never round-trips HBM between the einsum and the softmax);
  a second streamed kernel applies the attention back over channels.
  Only the C×C attention map (≤1 MB at C=512) crosses HBM between the
  two.

Backward for both: a ``jax.custom_vjp`` whose reverse pass recomputes
with the O(N·block) / jnp reference form and differentiates that —
recompute-not-store, the standard flash trade.

Tests run these kernels with ``interpret=True`` on CPU (pallas's
interpreter executes the same program the Mosaic compiler lowers on
TPU), including forward AND backward parity against the XLA forms.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import blocked_position_attention, channel_attention

_NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, s_ref, acc_ref,
                  *, n_real: int, block_k: int, scale: float | None):
    """One (q-block, k-block) tile of online-softmax attention."""
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        s_ref[:] = jnp.zeros_like(s_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0]          # (bq, ck)
    k = k_ref[0]          # (bk, ck)
    v = v_ref[0]          # (bk, cv)
    scores = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)          # (bq, bk)
    if scale is not None:
        scores = scores * scale
    # Mask keys past the true token count (N was padded to a block multiple).
    key_idx = j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, scores.shape, 1)
    scores = jnp.where(key_idx < n_real, scores, _NEG_INF)

    m_prev = m_ref[:, :1]                            # (bq, 1)
    m_new = jnp.maximum(m_prev, scores.max(axis=-1, keepdims=True))
    corr = jnp.exp(m_prev - m_new)
    p = jnp.exp(scores - m_new)                      # (bq, bk)
    s_new = s_ref[:, :1] * corr + p.sum(axis=-1, keepdims=True)
    acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    s_ref[:] = jnp.broadcast_to(s_new, s_ref.shape)

    @pl.when(j == nk - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:] / jnp.maximum(s_ref[:, :1], 1e-30)
                    ).astype(o_ref.dtype)


def _flash_forward(q, k, v, block_q: int, block_k: int,
                   scale: float | None, interpret: bool | None):
    if interpret is None:
        # Mosaic compiles on TPU; everywhere else run the same program in
        # the pallas interpreter (slow but correct — CI / CPU meshes).
        interpret = jax.default_backend() != "tpu"
    b, n, ck = q.shape
    cv = v.shape[-1]
    nq = pl.cdiv(n, block_q)
    nk = pl.cdiv(n, block_k)
    pad_q = nq * block_q - n
    pad_k = nk * block_k - n
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0)))

    kernel = functools.partial(_flash_kernel, n_real=n, block_k=block_k,
                               scale=scale)
    out = pl.pallas_call(
        kernel,
        grid=(b, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, ck), lambda b_, i, j: (b_, i, 0)),
            pl.BlockSpec((1, block_k, ck), lambda b_, i, j: (b_, j, 0)),
            pl.BlockSpec((1, block_k, cv), lambda b_, i, j: (b_, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, cv), lambda b_, i, j: (b_, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, nq * block_q, cv), v.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),   # running max
            pltpu.VMEM((block_q, 128), jnp.float32),   # running sum
            pltpu.VMEM((block_q, cv), jnp.float32),    # accumulator
        ],
        interpret=interpret,
    )(q, k, v)
    return out[:, :n, :]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_position_attention(q, k, v, block_q: int = 256, block_k: int = 256,
                             scale: float | None = None,
                             interpret: bool | None = None):
    """Flash position attention: same math as
    :func:`ops.attention.position_attention` (unscaled DANet energies unless
    ``scale``), O(N·block) memory, MXU-scheduled.

    ``q``/``k``: (B, N, Ck); ``v``: (B, N, Cv) -> (B, N, Cv).
    """
    return _flash_forward(q, k, v, block_q, block_k, scale, interpret)


def _fwd(q, k, v, block_q, block_k, scale, interpret):
    out = _flash_forward(q, k, v, block_q, block_k, scale, interpret)
    return out, (q, k, v)


def _bwd(block_q, block_k, scale, interpret, res, g):
    q, k, v = res
    # Recompute with the O(N*block) jnp form and differentiate that — the
    # flash backward without a second hand-written kernel.
    def ref(q_, k_, v_):
        if scale is not None:  # score scaling == scaling q
            q_ = q_ * scale
        return blocked_position_attention(q_, k_, v_, block_size=block_k)
    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(g)


flash_position_attention.defvjp(_fwd, _bwd)


# ---------------------------------------------------- channel (gram) branch

def _cam_energy_kernel(x_ref, attn_ref, energy_ref):
    """Fused gram + softmax: accumulate Xᵀ·X over row blocks in VMEM
    scratch; on the last block run DANet's max-subtraction softmax on
    the VPU and emit the (C, C) attention map.  Zero-padded rows (N not
    a block multiple) contribute zero to the gram — no masking needed."""
    j = pl.program_id(1)
    nb = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        energy_ref[:] = jnp.zeros_like(energy_ref)

    x = x_ref[0]  # (block_n, C)
    energy_ref[:] += jax.lax.dot_general(
        x, x, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)          # (C, C)

    @pl.when(j == nb - 1)
    def _finalize():
        energy = energy_ref[:]
        # DANet CAM: attend to the LEAST similar channels — rowmax - E
        energy = energy.max(axis=-1, keepdims=True) - energy
        m = energy.max(axis=-1, keepdims=True)
        p = jnp.exp(energy - m)
        attn_ref[0] = (p / p.sum(axis=-1, keepdims=True)
                       ).astype(attn_ref.dtype)


def _cam_apply_kernel(attn_ref, x_ref, o_ref):
    """Streamed apply: out row block = X_block · Attnᵀ (MXU), the
    attention map resident in VMEM for the whole sweep."""
    x = x_ref[0].astype(jnp.float32)  # (block_n, C)
    attn = attn_ref[0]                # (C, C), f32
    o_ref[0] = jax.lax.dot_general(
        x, attn, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32).astype(o_ref.dtype)


def _cam_forward(x, block_n: int, interpret: bool | None):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, n, c = x.shape
    nb = pl.cdiv(n, block_n)
    pad = nb * block_n - n
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    attn = pl.pallas_call(
        _cam_energy_kernel,
        grid=(b, nb),
        in_specs=[pl.BlockSpec((1, block_n, c), lambda b_, j: (b_, j, 0))],
        out_specs=pl.BlockSpec((1, c, c), lambda b_, j: (b_, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, c, c), jnp.float32),
        scratch_shapes=[pltpu.VMEM((c, c), jnp.float32)],
        interpret=interpret,
    )(x)
    out = pl.pallas_call(
        _cam_apply_kernel,
        grid=(b, nb),
        in_specs=[
            pl.BlockSpec((1, c, c), lambda b_, j: (b_, 0, 0)),
            pl.BlockSpec((1, block_n, c), lambda b_, j: (b_, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_n, c), lambda b_, j: (b_, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b, nb * block_n, c), x.dtype),
        interpret=interpret,
    )(attn, x)
    return out[:, :n, :]


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def flash_channel_attention(x, block_n: int = 256,
                            interpret: bool | None = None):
    """Fused channel (gram-matrix) attention: same math as
    :func:`ops.attention.channel_attention` — C×C gram of the (B, N, C)
    tokens, max-subtraction softmax, applied back over channels — with
    the gram accumulation and softmax fused into one VMEM-resident
    kernel and the apply streamed.  ``(B, N, C) -> (B, N, C)``."""
    return _cam_forward(x, block_n, interpret)


def _cam_fwd(x, block_n, interpret):
    return _cam_forward(x, block_n, interpret), (x,)


def _cam_bwd(block_n, interpret, res, g):
    (x,) = res
    # Recompute with the jnp reference form and differentiate that — the
    # gram is cheap to rebuild (one (C, C) matmul) vs storing the
    # attention map's softmax residuals.
    _, vjp = jax.vjp(channel_attention, x)
    return vjp(g)


flash_channel_attention.defvjp(_cam_fwd, _cam_bwd)
