"""Device-side ragged resizes as separable weight matmuls.

The full-res semantic val protocol (reference train_pascal.py:280-306
generalized to multi-class — metric at ORIGINAL resolution) needs every
sample's crop-space class probabilities resized to that sample's own
native size.  Ragged per-image work was host-bound in rounds 2-3
(BASELINE.md: 1.5 imgs/s — one 21-channel cv2 resize per image on a
1-core host, after shipping a 22 MB probability volume over the wire).

TPU-native formulation: bilinear resize to a *per-sample* target size is
a pair of matmuls with weight matrices built from compares over a static
padded grid — ``W_h[o, i] = tent(src_center(o) - i)`` — so one jitted,
vmapped program handles every native size up to ``val_max_im_size`` with
static shapes, no gathers (the r4 lesson: XLA lowers gathers to ~1.6
GiB/s scalar loops on TPU, ``prof_deeplab_b8.json``), and MXU-friendly
contractions.  Only the argmax CLASS MAP (uint8, 21x fewer bytes than
the bf16 probability volume) crosses the wire; the host slices each
sample's valid region and bincounts the confusion matrix.

Weight semantics pin cv2.INTER_LINEAR (the imaging backend the host path
uses, ``imaging.resize``): half-pixel centers ``src = (dst + 0.5) *
(in / out) - 0.5`` clamped to the valid range (edge replicate), a plain
tent — cv2 applies no antialias prefilter for INTER_LINEAR in either
direction, so the same weights hold for the protocol's slight downscales
(513² crop -> ≤500² native) as for upscales.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _linear_weight_matrix(out_size: jax.Array, n_out: int,
                          in_size: int) -> jax.Array:
    """(n_out, in_size) bilinear weights for a traced per-sample target.

    Rows at or beyond ``out_size`` are zeroed; callers mask/slice them.
    Built from iota compares only — no gather, no dynamic shape.
    """
    out_size = jnp.asarray(out_size, jnp.float32)
    o = jnp.arange(n_out, dtype=jnp.float32)
    src = (o + 0.5) * (jnp.float32(in_size) / out_size) - 0.5
    src = jnp.clip(src, 0.0, jnp.float32(in_size - 1))
    lo = jnp.floor(src)
    frac = src - lo
    i = jnp.arange(in_size, dtype=jnp.float32)
    is_lo = i[None, :] == lo[:, None]
    is_hi = i[None, :] == (lo[:, None] + 1.0)
    w = is_lo * (1.0 - frac[:, None]) + is_hi * frac[:, None]
    return jnp.where(o[:, None] < out_size, w, 0.0)


def resize_bilinear_ragged(x: jax.Array, out_hw: jax.Array,
                           max_hw: tuple[int, int]) -> jax.Array:
    """Per-sample bilinear resize of ``x`` (B, H, W, C) to each sample's
    ``out_hw[b] = (h_b, w_b)`` inside a static (B, max_h, max_w, C) canvas.

    Rows/cols beyond a sample's own size are zero.  f32 arithmetic
    matching the host path (which widens to f32 before cv2).
    """
    max_h, max_w = int(max_hw[0]), int(max_hw[1])
    in_h, in_w = x.shape[1], x.shape[2]

    def one(xi, hw):
        wh = _linear_weight_matrix(hw[0], max_h, in_h)
        ww = _linear_weight_matrix(hw[1], max_w, in_w)
        y = jnp.einsum("oi,iwc->owc", wh, xi.astype(jnp.float32))
        return jnp.einsum("pj,ojc->opc", ww, y)

    return jax.vmap(one)(x, out_hw)


@functools.partial(jax.jit, static_argnums=(2,))
def fullres_argmax(probs: jax.Array, out_hw: jax.Array,
                   max_hw: tuple[int, int]) -> jax.Array:
    """Device half of the full-res semantic protocol: resize class
    probabilities (B, H, W, C) to each sample's native size and argmax.

    Returns (B, max_h, max_w) uint8 class ids — the only array that
    crosses the wire; callers slice ``[:h_b, :w_b]`` per sample before
    scoring (out-of-range pixels are argmax-of-zeros and must not be
    scored).
    """
    if probs.shape[-1] > 256:
        raise ValueError(
            f"{probs.shape[-1]} classes do not fit the uint8 class-map "
            "wire; use resize_bilinear_ragged + argmax directly")
    full = resize_bilinear_ragged(probs, out_hw, max_hw)
    return jnp.argmax(full, axis=-1).astype(jnp.uint8)
