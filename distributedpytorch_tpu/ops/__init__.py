"""Compute primitives: attention, losses, metrics.

These are the pure-function kernels under the model layer — the part of the
reference that lived in external CUDA packages (PyTorch-Encoding's DANet
attention blocks, ``SegmentationMultiLosses``; reference train_pascal.py:32-33)
re-expressed as XLA-compiled jnp (with Pallas variants for the hot attention
path).
"""

from . import augment
from . import guidance_device
from .attention import (
    position_attention,
    blocked_position_attention,
    channel_attention,
)
from .pallas_attention import (
    flash_channel_attention,
    flash_position_attention,
)
from .losses import (
    sigmoid_balanced_bce,
    multi_output_loss,
    se_presence_loss,
    softmax_xent_ignore,
)
from .metrics import (
    batched_jaccard,
    confusion_matrix,
    jaccard,
    miou_from_confusion,
    threshold_sweep_jaccard,
)
from .warp import fullres_argmax, resize_bilinear_ragged

__all__ = [
    "augment",
    "guidance_device",
    "position_attention",
    "blocked_position_attention",
    "channel_attention",
    "flash_channel_attention",
    "flash_position_attention",
    "sigmoid_balanced_bce",
    "multi_output_loss",
    "se_presence_loss",
    "softmax_xent_ignore",
    "jaccard",
    "batched_jaccard",
    "confusion_matrix",
    "miou_from_confusion",
    "threshold_sweep_jaccard",
    "fullres_argmax",
    "resize_bilinear_ragged",
]
