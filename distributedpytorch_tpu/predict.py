"""Interactive-segmentation inference: extreme-point clicks -> full-res mask.

The reference trains a click-guided (DEXTR-style) binary segmenter but ships
no inference entry point — its val loop (reference train_pascal.py:233-308)
is the only consumer of the trained model.  This module completes that user
story: given an RGB image and the 4 extreme points of the object (the same
guidance the model was trained on, reference custom_transforms.py:30-51), it
runs the full preprocessing -> model -> paste-back chain and returns a
full-resolution probability mask.

The preprocessing mirrors the *val* transform pipeline exactly
(reference train_pascal.py:135-145), with the clicked points standing in for
the gt-derived deterministic extreme points:

    points -> relax-padded bbox        (CropFromMaskStatic semantics, relax=50)
           -> zero-padded crop         (helpers.crop_from_mask)
           -> fixed resize             (FixedResize, cubic, 512x512)
           -> n-ellipse + gaussians    (NEllipseWithGaussians, z1 + alpha*z2,
                                        rescaled to peak 255)
           -> RGB(3) + guidance(1)     (ConcatInputs -> 'concat', [0,255])

and the postprocessing mirrors the val metric path (train_pascal.py:283-290):
sigmoid of the fused head, ``crop2fullmask`` paste-back with the relax border
shaved.

Device work is one jitted forward at a fixed (resolution, 4) shape, so every
click/image after the first reuses the same compiled program — the
interactive-latency design point.
"""

from __future__ import annotations

import os
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import imaging
from .data import guidance as guidance_lib
from .utils.helpers import crop2fullmask, crop_from_bbox, get_bbox


#: guidance families computable from the 4 clicks alone — the ones
#: click-based inference can serve (confidence maps need the gt mask,
#: 'none' has no channel).  Single source of truth lives in
#: data/guidance.py (``POINT_GUIDANCE``), shared with session-log replay
#: (data/sessions.py) so serve-time and replay-time guidance are one
#: implementation; the pre-restore guards in ``Predictor.from_run``/
#: ``from_torch`` AND ``guidance_from_points``' dispatch both read it,
#: so a family cannot be accepted at construction yet unknown at
#: predict time.
_POINT_GUIDANCE = guidance_lib.POINT_GUIDANCE

#: re-export: the dispatch moved to data/guidance.py (numpy-only, so the
#: flywheel's replay reader can use it without importing jax); the public
#: name here is unchanged.
guidance_from_points = guidance_lib.guidance_from_points


def prepare_input(
    image: np.ndarray,
    points: np.ndarray,
    relax: int = 50,
    zero_pad: bool = True,
    resolution: tuple[int, int] = (512, 512),
    alpha: float = 0.6,
    guidance: str = "nellipse_gaussians",
) -> tuple[np.ndarray, tuple[int, int, int, int]]:
    """Image + clicks -> (``concat`` (H, W, 4) float32, crop bbox).

    ``image`` is (H, W, 3) RGB, any dtype, values in [0, 255]; ``points`` is
    (4, 2) xy in full-image coordinates.  Returns the network input at
    ``resolution`` and the (relax-padded) bbox needed to paste the prediction
    back with :func:`predict` / ``crop2fullmask``.  ``guidance`` must match
    the family the checkpoint was trained with (see
    :func:`guidance_from_points`).
    """
    image = np.asarray(image, np.float32)
    if image.ndim != 3 or image.shape[-1] != 3:
        raise ValueError(f"expected (H, W, 3) RGB image, got {image.shape}")
    points = np.asarray(points, np.float64)
    if points.shape != (4, 2):
        raise ValueError(f"expected 4 xy extreme points, got {points.shape}")
    h, w = image.shape[:2]
    if (points[:, 0].max() >= w or points[:, 1].max() >= h
            or points.min() < 0):
        raise ValueError(f"points {points.tolist()} outside image {w}x{h}")

    # get_bbox only reads .shape when points are given; a broadcast stub
    # avoids allocating an image-sized array per click.
    shape_stub = np.broadcast_to(np.uint8(0), (h, w))
    bbox = get_bbox(shape_stub, points=points, pad=relax, zero_pad=zero_pad)
    crop = crop_from_bbox(image, bbox, zero_pad=zero_pad)
    res_h, res_w = resolution
    crop = imaging.resize(crop, (res_h, res_w), imaging.CUBIC)
    # Points into resized-crop coordinates + guidance synthesis, through
    # the shared seam (data/guidance.py:crop_point_guidance) — the same
    # call session-log replay makes, pinning bit-identity.
    heat = guidance_lib.crop_point_guidance(
        points, bbox, (res_h, res_w), alpha=alpha, family=guidance)
    concat = np.concatenate(
        [np.clip(crop, 0.0, 255.0), heat[..., None]], axis=-1)
    return concat.astype(np.float32), bbox


def load_run_config(run_dir: str):
    """The run's saved ``Config`` (cheap — no checkpoint IO), so callers can
    validate task/guidance compatibility before paying for the restore."""
    from .train import config as config_lib

    return config_lib.from_json(os.path.join(run_dir, "config.json"))


def model_from_config(cfg):
    """Rebuild the model exactly as the Trainer did, minus mesh couplings:
    ring PAM needs a sequence-parallel mesh, so inference falls back to the
    numerically identical einsum form, and the bucketed-reduce run's
    cross-replica BN stays off (train-time only; inference never computes
    batch stats).  The moe_* options shape the param tree and MUST match
    or checkpoint restore fails.  train.precision carries over: a
    bf16-trained run serves bf16 (master params are f32 either way, so
    restore is dtype-independent)."""
    from .models import build_model
    from .train.precision import precision_policy

    policy = precision_policy(
        getattr(getattr(cfg, "train", None), "precision", None))
    return build_model(
        name=cfg.model.name, nclass=cfg.model.nclass,
        backbone=cfg.model.backbone,
        output_stride=cfg.model.output_stride,
        dtype=(policy.compute_dtype if policy else cfg.model.dtype),
        pam_block_size=cfg.model.pam_block_size,
        attention_impl=getattr(cfg.model, "attention_impl", "auto"),
        pam_impl="einsum" if cfg.model.pam_impl == "ring"
        else cfg.model.pam_impl,
        pam_score_dtype=getattr(cfg.model, "pam_score_dtype", None),
        remat=cfg.model.remat,
        moe_experts=cfg.model.moe_experts,
        moe_hidden=cfg.model.moe_hidden, moe_k=cfg.model.moe_k,
        moe_capacity_factor=cfg.model.moe_capacity_factor,
        aux_head=cfg.model.aux_head,
        encnet_codes=getattr(cfg.model, "encnet_codes", 32),
        ccnet_recurrence=getattr(cfg.model, "ccnet_recurrence", 2),
        guidance_inject=getattr(cfg.model, "guidance_inject", "stem"))


def load_run(run_dir: str, best: bool = True, cfg=None):
    """Load ``(cfg, model, state)`` from a training run directory.

    ``cfg``: pass the run's already-loaded config (from
    :func:`load_run_config`) to skip re-reading it.

    Restores the best-metric checkpoint (falling back to latest) onto an
    abstract ``eval_shape`` template — Orbax restores onto
    ShapeDtypeStructs, so no throwaway second copy of the params is ever
    materialized.
    """
    from .parallel import create_train_state
    from .train.checkpoint import CheckpointManager
    from .train.optim import make_optimizer

    if cfg is None:
        cfg = load_run_config(run_dir)
    model = model_from_config(cfg)
    h, w = cfg.data.crop_size
    # The template's opt_state tree must match what the run saved, so the
    # optimizer comes from the run's own config (total_steps only shapes
    # the schedule, not the state tree).
    tx, _ = make_optimizer(cfg.optim, total_steps=1)
    template = jax.eval_shape(
        lambda: create_train_state(jax.random.PRNGKey(0), model, tx,
                                   (1, h, w, cfg.model.in_channels)))
    # Pin every leaf to THIS process's device 0: Orbax needs concrete
    # shardings on the abstract target whenever the checkpoint's own saved
    # layout isn't reconstructible here (e.g. a run trained on an 8-device
    # mesh, loaded in a 1-device export/predict process) — and a single
    # device is exactly where inference wants the weights anyway.
    one_dev = jax.sharding.SingleDeviceSharding(jax.local_devices()[0])
    template = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=one_dev),
        template)
    mgr = CheckpointManager(os.path.join(run_dir, "checkpoints"),
                            async_save=False)
    try:
        try:
            state, _ = mgr.restore(template, best=best)
        except FileNotFoundError:
            if not best:
                raise
            state, _ = mgr.restore(template, best=False)  # no best slot yet
    finally:
        mgr.close()
    return cfg, model, state


def _apply_with_normalize(model, variables, mean, std, x):
    """Optional mean/std normalization + model apply — the shared first
    half of both predictors' compiled forwards."""
    if mean is not None or std is not None:
        from .ops.augment import normalize
        x = normalize({"concat": x}, mean or (0.0,),
                      std or (255.0,))["concat"]
    return model.apply(variables, x, train=False)


def _split_channel_stats(vals, n_channels: int):
    """Split per-channel normalization stats into (rgb, guidance) parts.

    The encode/decode split normalizes each part inside its own stage;
    slicing here keeps that bitwise identical to normalizing the concat
    and then splitting.  A broadcast scalar applies to both parts;
    per-channel stats must cover every channel or the guidance lane
    would silently reuse an RGB constant.
    """
    if vals is None:
        return None, None
    vals = tuple(vals)
    if len(vals) == 1:
        return vals, vals
    if len(vals) != n_channels:
        raise ValueError(
            f"normalization stats have {len(vals)} entries for "
            f"{n_channels} input channels — pass 1 (broadcast) or "
            f"{n_channels} (per-channel incl. guidance)")
    return vals[:-1], vals[-1:]


def _click_kwargs_from_cfg(cfg, kwargs: dict) -> dict:
    """Default the click-predictor constructor kwargs from a run config."""
    kwargs.setdefault("resolution", tuple(cfg.data.crop_size))
    kwargs.setdefault("relax", cfg.data.relax)
    kwargs.setdefault("zero_pad", cfg.data.zero_pad)
    kwargs.setdefault("alpha", cfg.data.guidance_alpha)
    kwargs.setdefault("guidance", cfg.data.guidance)
    kwargs.setdefault("in_channels", cfg.model.in_channels)
    return kwargs


class _AotDispatch:
    """Route concrete-batch calls to an installed AOT executable
    (serve/aot.py), everything else to the underlying jitted callable.

    The pre-compiled executables a warm-cache serve boot deserializes
    (``jax.experimental.serialize_executable``) are ``jax.stages
    .Compiled`` objects outside the jit dispatch cache, so the predictor
    needs its own per-shape table.  The wrapper is transparent to every
    other consumer: tracing callers (``jax.eval_shape``, the jaxaudit
    lowering cache's ``fn.trace``/``fn.lower``) see the jit function —
    a Tracer argument, or any attribute access, falls straight through —
    and with an empty table the call overhead is one truthiness check.
    """

    # __weakref__: jax.eval_shape (feature_struct) weak-caches the callable
    __slots__ = ("_fn", "_table", "_key_of", "__weakref__")

    def __init__(self, fn, table: dict, key_of):
        self._fn = fn
        self._table = table
        self._key_of = key_of

    def __call__(self, *args):
        if self._table:
            x = args[0]
            shape = getattr(x, "shape", None)
            if shape is not None and not isinstance(x, jax.core.Tracer):
                exe = self._table.get(self._key_of(tuple(shape)))
                if exe is not None:
                    return exe(*args)
        return self._fn(*args)

    def __getattr__(self, name):
        # .trace / .lower / .__name__ / ... — the jit fn's own surface
        return getattr(object.__getattribute__(self, "_fn"), name)


class Predictor:
    """Reusable click-to-mask inference on one model + checkpoint.

    >>> p = Predictor.from_run("work/run_0")          # config.json + ckpt
    >>> prob = p.predict(image, points)               # (H, W) in [0, 1]
    >>> mask = prob > 0.5

    One compiled forward per (resolution, channels) shape; subsequent calls
    are dispatch-only.
    """

    def __init__(self, model, params, batch_stats,
                 resolution: tuple[int, int] = (512, 512),
                 relax: int = 50, zero_pad: bool = True,
                 alpha: float = 0.6,
                 guidance: str = "nellipse_gaussians",
                 mean: Sequence[float] | None = None,
                 std: Sequence[float] | None = None,
                 mesh=None, in_channels: int = 4):
        self.model = model
        self.resolution = tuple(resolution)
        #: network input channel count (RGB + guidance = 4 for the
        #: reference stem; exotic stems differ) — flax infers it lazily
        #: from the first call, so shape-building consumers (the serve
        #: warmup) read it here instead of guessing
        self.in_channels = in_channels
        self.relax = relax
        self.zero_pad = zero_pad
        self.alpha = alpha
        self.guidance = guidance
        self.mesh = mesh
        #: the served weights, as handed in — the hot-swap path
        #: (serve/swap.load_swap_predictor) and tests read them back;
        #: the compiled forwards close over this exact tree
        self.params = params
        self.batch_stats = batch_stats
        # NOTE: params may hold serve/quantize.QTensor leaves (int8
        # kernels + scales).  Nothing here special-cases them: flax's
        # dtype promotion calls ``jnp.asarray`` on every kernel at use,
        # which triggers QTensor.__jax_array__ — the dequantization is
        # traced INSIDE whichever jitted forward consumes the kernel,
        # and only the kernels a program actually uses enter its trace.
        variables = {"params": params, "batch_stats": batch_stats}
        #: per-shape AOT executables (serve/aot.py) — empty unless a
        #: warm-cache serve boot installed pre-compiled programs
        self._aot_execs: dict = {}

        def forward(x):
            outputs = _apply_with_normalize(model, variables, mean, std, x)
            # Fused (primary) head only — the tuple's first element, the one
            # the reference's metric consumes (train_pascal.py:283).
            return jax.nn.sigmoid(outputs[0].astype(jnp.float32))

        #: guidance_inject='head' models split into two separately-jitted
        #: stages: ``encode_jitted`` (RGB crop -> backbone features, the
        #: session-invariant ~90% of the FLOPs) and ``decode_jitted``
        #: (features + guidance -> probability maps).  Sessions are
        #: single-device (the feature cache pins one device's HBM), so a
        #: mesh predictor keeps the whole-forward jit and no stages.
        self.supports_sessions = (
            getattr(model, "guidance_inject", "stem") == "head"
            and mesh is None)
        self.encode_jitted = None
        self.decode_jitted = None
        if self.supports_sessions:
            from .ops.augment import normalize as _normalize

            rgb_mean, g_mean = _split_channel_stats(mean, in_channels)
            rgb_std, g_std = _split_channel_stats(std, in_channels)

            def _norm(x, m, s):
                if m is None and s is None:
                    return x
                return _normalize({"concat": x}, m or (0.0,),
                                  s or (255.0,))["concat"]

            def encode_forward(rgb):
                return model.apply(variables, _norm(rgb, rgb_mean, rgb_std),
                                   train=False, stage="encode")

            def decode_forward(feats, guidance):
                outs = model.apply(
                    variables, (feats, _norm(guidance, g_mean, g_std)),
                    train=False, stage="decode",
                    out_size=self.resolution)
                return jax.nn.sigmoid(outs[0].astype(jnp.float32))

            self.encode_jitted = _AotDispatch(
                jax.jit(encode_forward), self._aot_execs,
                lambda s: ("encode", s[0]))
            self.decode_jitted = _AotDispatch(
                jax.jit(decode_forward), self._aot_execs,
                lambda s: ("decode", s[0]))

            def staged_forward(x):
                # THE forward of a split predictor IS the composition, so
                # the stateless path and the session path (cached feats ->
                # decode) run the exact same two compiled programs — warm
                # and cold clicks are bitwise identical by construction.
                return self.decode_jitted(self.encode_jitted(x[..., :-1]),
                                          x[..., -1:])

            self._forward = staged_forward
        elif mesh is None:
            self._forward = _AotDispatch(jax.jit(forward), self._aot_execs,
                                         lambda s: ("forward", s))
        else:
            # Distributed inference: crops shard over the mesh's data axis
            # (GSPMD partitions the forward, same as the train step); the
            # probability maps come back replicated for the host paste-back.
            # Single-process only: shard_batch's multi-process branch treats
            # the input as a per-host shard, which would duplicate the whole
            # crop batch on every host here.
            if jax.process_count() > 1:
                raise ValueError(
                    "Predictor(mesh=...) is single-process (all local "
                    "devices); multi-host serving should shard requests "
                    "across processes instead")
            from .parallel.mesh import batch_sharding, replicated_sharding
            self._forward = jax.jit(
                forward, in_shardings=batch_sharding(mesh),
                out_shardings=replicated_sharding(mesh))

    @property
    def forward_jitted(self):
        """The exact forward this predictor dispatches — the callable the
        serve audit hooks and jaxaudit contracts trace (``analysis.ir``);
        one compiled program per batch shape.  For a split predictor
        (``supports_sessions``) this is the encode∘decode COMPOSITION
        (plain Python, not itself traceable) — audit the stages via
        ``encode_jitted``/``decode_jitted`` instead."""
        return self._forward

    def install_aot(self, key: tuple, executable) -> None:
        """Install a pre-compiled executable for one program shape.

        ``key``: ``("forward", (B, H, W, C))`` for a whole-forward
        predictor, ``("encode", bucket)`` / ``("decode", bucket)`` for a
        split one — the keys ``serve.aot.AotCache`` hands the warm-boot
        loader.  Dispatches at that exact shape then run the installed
        executable instead of the jit cache (zero compiles on a
        warm-cache boot); every other shape, and every tracing consumer,
        keeps the ordinary jitted path.
        """
        if self.mesh is not None:
            raise ValueError(
                "install_aot: mesh predictors compile GSPMD programs "
                "bound to this process's device assignment — the AOT "
                "cache serves single-device replicas")
        kind = key[0]
        valid = ({"encode", "decode"} if self.supports_sessions
                 else {"forward"})
        if kind not in valid:
            raise ValueError(
                f"install_aot: key kind {kind!r} does not match this "
                f"predictor's programs ({sorted(valid)})")
        self._aot_execs[key] = executable

    @property
    def aot_programs(self) -> list:
        """Keys of the installed AOT executables (ops surface)."""
        return sorted(self._aot_execs, key=str)

    def feature_struct(self, batch: int = 1):
        """ShapeDtypeStruct of one encoded-feature batch — the session
        cache entry's shape/dtype (and the byte cost the HBM budget
        charges), computed without dispatching."""
        if not self.supports_sessions:
            raise ValueError("feature_struct: this predictor has no "
                             "encode stage (guidance_inject='stem' or "
                             "mesh-sharded)")
        h, w = self.resolution
        rgb = jax.ShapeDtypeStruct((batch, h, w, self.in_channels - 1),
                                   jnp.float32)
        return jax.eval_shape(self.encode_jitted, rgb)

    def prepare_guidance(self, points: Any,
                         bbox: tuple[int, int, int, int]) -> np.ndarray:
        """Warm-click guidance: new clicks mapped into an EXISTING crop.

        A session's first click established ``bbox`` (and the cached
        backbone features of that crop); refinement clicks re-synthesize
        only the guidance channel in the same crop coordinates — the
        FixedResize point-scaling rule of :func:`prepare_input`, with the
        bbox held fixed.  Returns (H, W, 1) float32 at ``resolution``.
        """
        points = np.asarray(points, np.float64)
        if points.shape != (4, 2):
            raise ValueError(f"expected 4 xy extreme points, got "
                             f"{points.shape}")
        heat = guidance_lib.crop_point_guidance(
            points, bbox, self.resolution, alpha=self.alpha,
            family=self.guidance)
        return heat.astype(np.float32)[..., None]

    @classmethod
    def from_run(cls, run_dir: str, best: bool = True, cfg=None,
                 **kwargs) -> "Predictor":
        """Build from a training run directory (``config.json`` +
        ``checkpoints/``), restoring the best-metric checkpoint by default
        (falls back to latest when no best exists).  ``cfg`` skips
        re-reading an already-loaded run config."""
        if cfg is None:
            cfg = load_run_config(run_dir)
        if cfg.task != "instance":
            raise ValueError(
                f"Predictor is the click-guided instance path; this run was "
                f"trained with task={cfg.task!r} (use SemanticPredictor)")
        if cfg.data.guidance not in _POINT_GUIDANCE:
            raise ValueError(
                f"this run's guidance family ({cfg.data.guidance!r}) is not "
                "derivable from clicks alone (confidence maps need the gt "
                "mask; 'none' has no channel) — click-based prediction does "
                "not apply to it")
        cfg, model, state = load_run(run_dir, best=best, cfg=cfg)
        return cls(model, state.params, state.batch_stats,
                   **_click_kwargs_from_cfg(cfg, kwargs))

    @classmethod
    def from_torch(cls, path: str, cfg=None, partial: bool = False,
                   rename=None, **kwargs) -> "Predictor":
        """Serve a torch ``.pth`` state_dict directly — no training run
        needed.  The reference's own accumulated checkpoints (it always
        warm-started from one, train_pascal.py:103) become TPU predictors
        in one call.

        ``cfg`` defaults to :class:`train.Config`'s reference hyperparameter
        point (DANet-R101, 4-channel 512² input) — the architecture the
        reference's checkpoints were trained on.  ``rename`` maps foreign
        key naming onto this framework's (see utils.torch_interop);
        ``partial=True`` tolerates missing/extra keys (e.g. a re-sized
        head), keeping fresh-init values for the gaps.
        """
        from .train.config import Config
        from .utils.torch_interop import (
            load_torch_file,
            torch_state_dict_to_params,
        )

        cfg = cfg or Config()
        if cfg.task != "instance":
            raise ValueError("Predictor.from_torch serves the click-guided "
                             f"instance path; got task={cfg.task!r}")
        if cfg.data.guidance not in _POINT_GUIDANCE:
            raise ValueError(
                f"cfg's guidance family ({cfg.data.guidance!r}) is not "
                "derivable from clicks alone; click-based prediction does "
                "not apply to it")
        model = model_from_config(cfg)
        h, w = cfg.data.crop_size
        variables = model.init(
            jax.random.PRNGKey(0),
            jnp.zeros((1, h, w, cfg.model.in_channels), jnp.float32),
            train=False)
        init_params = variables["params"]
        init_stats = variables.get("batch_stats", {})

        # Shape-only templates so imported-vs-kept is distinguishable
        # (a concrete template leaf and a kept leaf would look identical).
        as_struct = lambda t: jax.tree.map(  # noqa: E731
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), t)
        params, stats = torch_state_dict_to_params(
            load_torch_file(path), as_struct(init_params),
            as_struct(init_stats), rename=rename,
            allow_missing=partial, allow_unused=partial)

        imported = [0, 0]  # [from checkpoint, kept fresh-init]

        def place(new, old):
            if isinstance(new, jax.ShapeDtypeStruct):
                imported[1] += 1
                return old
            imported[0] += 1
            return jnp.asarray(new)

        params = jax.tree.map(place, params, init_params)
        stats = jax.tree.map(place, stats, init_stats)
        if imported[0] == 0:
            raise ValueError(
                f"warm start from {path} imported 0 of {imported[1]} "
                "leaves — checkpoint keys do not match this model; check "
                "the architecture/naming (or pass a rename callable)")
        return cls(model, params, stats,
                   **_click_kwargs_from_cfg(cfg, kwargs))

    def prepare(self, image: np.ndarray,
                points: Any) -> tuple[np.ndarray, tuple[int, int, int, int]]:
        """:func:`prepare_input` with this predictor's settings: image +
        clicks -> (network input at ``self.resolution``, paste-back bbox).
        Pure host-side numpy — safe to run concurrently from many client
        threads (the serve front door does exactly that)."""
        return prepare_input(image, points, relax=self.relax,
                             zero_pad=self.zero_pad,
                             resolution=self.resolution,
                             alpha=self.alpha, guidance=self.guidance)

    def forward_prepared(self, concat: np.ndarray) -> np.ndarray:
        """(B, H, W, C) prepared crops -> (B, H, W) float32 probability
        maps — the raw batched compiled forward.

        The single code path under :meth:`predict_batch` AND the serve
        micro-batcher (serve/service.py): one compile per distinct leading
        batch dimension, every later call at that B is dispatch-only.  A
        single (H, W, C) crop is accepted and treated as B=1.  Per-lane
        results are independent of the other lanes' CONTENT (eval-mode
        BN, per-sample attention) — at a fixed batch shape a lane is
        bitwise reproducible whatever rides alongside it, which is what
        lets the serve batcher pad with dead lanes at no numerical cost.
        With a ``mesh``, the batch additionally pads/shards over the data
        axis (the jit's in_shardings owns device placement).
        """
        concat = np.asarray(concat, np.float32)
        if concat.ndim == 3:
            concat = concat[None]
        if self.mesh is not None:
            # Pad to the data-axis extent only (a model axis does not shard
            # the batch); the jit's in_shardings owns the device placement.
            from .parallel.mesh import DATA_AXIS, pad_to_multiple
            padded, n = pad_to_multiple({"concat": concat},
                                        self.mesh.shape[DATA_AXIS])
            return np.asarray(self._forward(padded["concat"]))[:n, ..., 0]
        return np.asarray(self._forward(concat))[..., 0]

    def paste_back(self, prob: np.ndarray, bbox: tuple[int, int, int, int],
                   shape_hw: tuple[int, int]) -> np.ndarray:
        """One crop-space probability map -> full-image coordinates with
        the relax border shaved (the val metric's mask_relax paste-back,
        reference train_pascal.py:290)."""
        return np.clip(crop2fullmask(prob, bbox, shape_hw,
                                     zero_pad=self.zero_pad,
                                     relax=self.relax),
                       0.0, 1.0)

    def predict(self, image: np.ndarray, points: Any) -> np.ndarray:
        """(H, W, 3) image + (4, 2) xy clicks -> (H, W) float32 probability
        mask in full-image coordinates (relax border shaved, as in the val
        metric path, reference train_pascal.py:290)."""
        return self.predict_batch(image, [points])[0]

    def predict_batch(self, image: np.ndarray,
                      points_list: Sequence[Any]) -> list[np.ndarray]:
        """Segment N objects of one image in a single device dispatch.

        ``points_list``: N click sets -> list of N full-res probability
        masks (same contract as :meth:`predict`).  All N crops go through
        one batched forward — the all-objects-of-an-image labeling case at
        1/N the dispatch overhead.  One compile per distinct N; reuse the
        same N (padding with repeats if needed) to stay dispatch-only, or
        use ``serve.InferenceService`` which pads to power-of-two buckets
        for you.  With a ``mesh``, the crop batch shards over the data
        axis (padded to its extent) — multi-chip inference with no other
        changes.
        """
        if len(points_list) == 0:  # not `not points_list`: ndarray-safe
            return []
        prepared = [self.prepare(image, pts) for pts in points_list]
        probs = self.forward_prepared(np.stack([c for c, _ in prepared]))
        return [self.paste_back(probs[i], bbox, image.shape[:2])
                for i, (_, bbox) in enumerate(prepared)]


class SemanticPredictor:
    """Whole-image multi-class inference for ``task='semantic'`` runs.

    Mirrors the semantic eval pipeline (pipeline.py:
    build_semantic_eval_transform): fixed resize to the training crop size,
    forward, per-pixel argmax of the primary head, nearest-resize of the
    class map back to the input size (class ids must stay exact).

    >>> p = SemanticPredictor.from_run("work/run_0")
    >>> classes = p.predict(image)       # (H, W) uint8 class ids
    """

    def __init__(self, model, params, batch_stats,
                 resolution: tuple[int, int] = (513, 513),
                 mean: Sequence[float] | None = None,
                 std: Sequence[float] | None = None):
        self.model = model
        self.resolution = tuple(resolution)
        variables = {"params": params, "batch_stats": batch_stats}

        def forward(x):
            outputs = _apply_with_normalize(model, variables, mean, std, x)
            # Argmax on device: one (H, W) int map crosses the wire, not
            # the (H, W, C) logits.
            return jnp.argmax(outputs[0], axis=-1).astype(jnp.int32)

        def forward_probs(x):
            outputs = _apply_with_normalize(model, variables, mean, std, x)
            return jax.nn.softmax(outputs[0].astype(jnp.float32), axis=-1)

        self._forward = jax.jit(forward)
        self._forward_probs = jax.jit(forward_probs)

    @classmethod
    def from_run(cls, run_dir: str, best: bool = True, cfg=None,
                 **kwargs) -> "SemanticPredictor":
        if cfg is None:
            cfg = load_run_config(run_dir)
        if cfg.task != "semantic":
            raise ValueError(
                f"SemanticPredictor is the whole-image multi-class path; "
                f"this run was trained with task={cfg.task!r} (use "
                f"Predictor for click-guided instance runs)")
        cfg, model, state = load_run(run_dir, best=best, cfg=cfg)
        kwargs.setdefault("resolution", tuple(cfg.data.crop_size))
        return cls(model, state.params, state.batch_stats, **kwargs)

    def predict(self, image: np.ndarray, mode: str = "resize",
                overlap: float = 0.5) -> np.ndarray:
        """(H, W, 3) RGB in [0, 255] -> (H, W) class-id map.

        ``mode='resize'`` (default): squeeze the whole image to the training
        resolution and nearest-resize the class map back — the eval
        pipeline's protocol, one forward.  ``mode='slide'``: tile the image
        at native resolution with training-crop-sized windows (stride =
        ``(1 - overlap) * crop``), average the softmax probabilities where
        windows overlap, argmax once — the standard full-resolution protocol
        for images larger than the crop.  Every window is the same fixed
        shape, so sliding costs ONE compiled program regardless of image
        size.

        uint8 when the model's class count fits (the PNG-writable common
        case); int32 otherwise — never a silent modulo-256 wrap."""
        image = np.asarray(image, np.float32)
        if image.ndim != 3 or image.shape[-1] != 3:
            raise ValueError(f"expected (H, W, 3) RGB image, got "
                             f"{image.shape}")
        dtype = np.uint8 if self.model.nclass <= 256 else np.int32
        if mode == "resize":
            resized = imaging.resize(np.clip(image, 0.0, 255.0),
                                     self.resolution, imaging.CUBIC)
            classes = np.asarray(self._forward(resized[None]))[0]
            full = imaging.resize(classes.astype(np.float32),
                                  image.shape[:2], imaging.NEAREST)
            return full.astype(dtype)
        if mode != "slide":
            raise ValueError(f"unknown mode {mode!r} (resize | slide)")
        if not 0.0 <= overlap < 1.0:
            raise ValueError(f"overlap must be in [0, 1), got {overlap}")
        ch, cw = self.resolution
        h, w = image.shape[:2]
        hp, wp = max(h, ch), max(w, cw)
        padded = np.zeros((hp, wp, 3), np.float32)
        padded[:h, :w] = np.clip(image, 0.0, 255.0)

        def starts(full: int, crop: int, stride: int) -> list[int]:
            s = list(range(0, full - crop + 1, stride))
            if s[-1] != full - crop:  # final window flush to the edge
                s.append(full - crop)
            return s

        sh = max(1, int(ch * (1.0 - overlap)))
        sw = max(1, int(cw * (1.0 - overlap)))
        probs = np.zeros((hp, wp, self.model.nclass), np.float32)
        for y in starts(hp, ch, sh):
            for x in starts(wp, cw, sw):
                win = padded[y:y + ch, x:x + cw]
                p = np.asarray(self._forward_probs(win[None]))[0]
                probs[y:y + ch, x:x + cw] += p
        # summed probs suffice: the per-pixel hit count is a positive scalar
        # across the class axis, so dividing by it cannot change the argmax
        classes = np.argmax(probs, axis=-1)
        return classes[:h, :w].astype(dtype)


def parse_points(spec: str) -> np.ndarray:
    """CLI point syntax: ``"x1,y1 x2,y2 x3,y3 x4,y4"`` (or ;-separated)."""
    parts = spec.replace(";", " ").split()
    try:
        pts = np.array([[float(v) for v in p.split(",")] for p in parts])
    except ValueError as e:
        raise ValueError(f"bad --points {spec!r}: {e}") from e
    if pts.shape != (4, 2):
        raise ValueError(
            f"--points needs exactly 4 x,y pairs, got shape {pts.shape}")
    return pts


def predict_cli(run_dir: str, image_path: str, points_spec: str | None,
                out_path: str, threshold: float | None = None,
                overlay_path: str | None = None,
                slide: bool = False) -> dict:
    """The ``--predict`` CLI body; dispatches on the run's task.

    Instance runs need ``points_spec`` (the 4 clicks) and write a binary
    mask PNG (``threshold`` defaults to 0.5); semantic runs take the whole
    image and write a class-id PNG — passing clicks or a threshold to one
    is an error, not a silent drop.  Returns a small summary dict.
    """
    from PIL import Image

    from .utils.helpers import overlay_mask

    cfg = load_run_config(run_dir)
    image = np.asarray(Image.open(image_path).convert("RGB"))

    def write_overlay(mask: np.ndarray) -> None:
        if overlay_path:
            over = overlay_mask(image.astype(np.float32) / 255.0,
                                mask.astype(np.float32))
            Image.fromarray((np.clip(over, 0, 1) * 255).astype(np.uint8)
                            ).save(overlay_path)

    if cfg.task == "semantic":
        if points_spec or threshold is not None:
            raise ValueError(
                "this run is task='semantic' (whole-image class map): "
                "--points/--threshold do not apply")
        classes = SemanticPredictor.from_run(run_dir, cfg=cfg).predict(
            image, mode="slide" if slide else "resize")
        Image.fromarray(classes).save(out_path)
        write_overlay(classes > 0)
        present = {int(c): int(n) for c, n in
                   zip(*np.unique(classes, return_counts=True))}
        return {"task": "semantic", "classes": present, "out": out_path,
                "mode": "slide" if slide else "resize"}

    if slide:
        raise ValueError("this run is task='instance' (click-guided crop "
                         "inference): --slide does not apply")
    if not points_spec:
        raise ValueError("this run is task='instance': --points (the 4 "
                         "extreme-point clicks) is required")
    threshold = 0.5 if threshold is None else threshold
    prob = Predictor.from_run(run_dir, cfg=cfg).predict(
        image, parse_points(points_spec))
    mask = prob > threshold
    Image.fromarray((mask * 255).astype(np.uint8)).save(out_path)
    write_overlay(mask)
    return {"task": "instance", "pixels": int(mask.sum()),
            "threshold": threshold, "max_prob": float(prob.max()),
            "out": out_path}


# ---------------------------------------------------------------------------
# Serialized compiled inference (jax.export / StableHLO)
# ---------------------------------------------------------------------------

def export_serialized(predictor, path: str, batch: int | None = None,
                      channels: int | None = None,
                      platforms: Sequence[str] = ("cpu", "tpu")) -> dict:
    """Serialize a predictor's compiled forward as a portable StableHLO
    artifact (``jax.export``) — the deployment-artifact story the torch
    ecosystem gets from TorchScript/ONNX export, done the XLA-native way.

    The artifact freezes weights + graph at the predictor's resolution and
    channel count and runs WITHOUT this package (any process with jax can
    :func:`load_serialized` it), on every platform in ``platforms``
    (multi-platform lowering: one file serves cpu and tpu).

    ``batch=None`` exports with a SYMBOLIC batch dimension — one artifact
    serves any batch size; pass a concrete int to pin it instead (smaller
    artifact, and the fallback when a model's ops reject polymorphism).

    Works for both :class:`Predictor` (output: sigmoid probability maps)
    and :class:`SemanticPredictor` (output: int32 class-id maps); mesh-
    sharded predictors are refused — GSPMD shardings are a property of
    this process's mesh, not of a portable artifact.
    """
    from jax import export as jax_export

    if getattr(predictor, "mesh", None) is not None:
        raise ValueError(
            "export_serialized: predictor was built with mesh=...; "
            "sharded inference is process-local — build an unsharded "
            "Predictor for export")
    ch = channels
    if ch is None:
        # the click path feeds RGB + one guidance channel; the semantic
        # path plain RGB (pipeline contract, prepare_input /
        # build_semantic_eval_transform) — exotic stems pass channels=
        ch = 4 if isinstance(predictor, Predictor) else 3
    if batch is None:
        (b,) = jax_export.symbolic_shape("b")
    else:
        b = int(batch)
    spec = jax.ShapeDtypeStruct((b, *predictor.resolution, ch),
                                jnp.float32)
    exported = jax_export.export(
        predictor._forward, platforms=list(platforms))(spec)
    blob = exported.serialize()
    with open(path, "wb") as f:
        f.write(blob)
    return {"path": path, "bytes": len(blob),
            "input_shape": tuple(str(d) for d in spec.shape),
            "platforms": tuple(platforms)}


def load_serialized(path: str):
    """Load an :func:`export_serialized` artifact into a callable.

    Pure jax on the consumer side — none of this package's model or config
    code runs; weights live inside the artifact.  The call is jitted, so
    repeat invocations at one shape are dispatch-only.
    """
    from jax import export as jax_export

    with open(path, "rb") as f:
        exported = jax_export.deserialize(f.read())
    return jax.jit(exported.call)
