"""Named injection sites + the process-wide arming flag.

The seams are woven into the REAL code paths (not shadow copies):

* ``trainer/batch_fetch``    — the trainer's batch-fetch boundary, inside
  the same ``input_wait`` goodput account the telemetry already books;
* ``trainer/train_step``     — after each compiled train-step dispatch
  (payload = the loss output; sigterm here is "preempted between steps");
* ``checkpoint/save``        — after a checkpoint save is enqueued/landed
  (``path`` ctx = the step directory, the truncation fault's target);
* ``checkpoint/restore``     — before a checkpoint restore;
* ``serve/enqueue``          — the serve front door (submit);
* ``serve/drain``            — the batcher worker, before the forward;
* ``serve/swap_params``      — the hot-swap param-load boundary
  (serve/swap.load_swap_predictor; payload = the restored param tree, so
  a ``nan`` fault models a poisoned/torn checkpoint arriving via swap —
  the canary-rollback scenario's trigger);
* ``serve/aot_load``         — the AOT executable cache's entry read
  (serve/aot.py), BEFORE the per-entry crc gate: a ``bitflip`` fault
  here models bit rot / a torn cache entry and must surface as the
  typed ``AotCacheError`` -> loud fresh-compile fallback, never a
  corrupt executable taking traffic (the ``stale_aot_cache``
  scenario's driver);
* ``device/put``             — host->device placement in the prefetcher;
* ``data/packed_read``       — the packed data plane's verified record
  read (data/packed.py), BEFORE the crc gate: a ``bitflip`` fault here
  models bit rot / a torn read and must surface as the typed
  ``PackedRecordError`` naming the record, never a silent wrong sample
  (the ``torn_pack`` scenario's driver);
* ``serve/session_append``   — the session-log sink's example boundary
  (serve/session_log.py), before the blob is checksummed and written:
  a ``nan`` fault here poisons the logged example exactly as a corrupt
  client/annotation pipeline would — the float crop NaN-fills, the
  crc then seals the poison in as VALID bytes — feeding the
  ``poisoned_flywheel`` scenario's sentinel/canary containment chain;
* ``serve/route``            — the fleet front's proxy path
  (serve/fleet.py), after the body's routing fields are read and
  before a replica is chosen: an ``error`` fault here is a routing
  failure the front must turn into a typed 503 shed, never an
  untyped 500 (note the ``sigkill`` fault kind kills the process that
  fires the site — armed in a REPLICA via ``DPTPU_CHAOS_PLAN`` on
  ``serve/drain``, that's the ``replica_kill_under_load`` scenario's
  mid-burst replica death);
* ``serve/health_poll``      — the fleet's health loop, before each
  replica's /healthz GET: latency faults model a slow replica, error
  faults a poll that never lands — both must flow through the
  per-replica Retry/CircuitBreaker membership machinery, never crash
  the poll thread.

Disabled is the default and it is ~free: ``fire`` loads one module
attribute, sees ``None`` and returns — no registry, no telemetry, no
allocation.  ``arm()`` installs a :class:`faults.FaultPlan`
process-wide; ``armed_plan()`` scopes one to a ``with`` block;
``maybe_arm_from_env()`` arms from ``DPTPU_CHAOS_PLAN`` (a JSON file
path or inline JSON) so any entry point can be chaos-tested without
code changes.
"""

from __future__ import annotations

import contextlib
import functools
import json
import os

from .faults import FaultPlan

#: the single armed plan (None = chaos disabled, the ~zero-overhead path)
_PLAN: FaultPlan | None = None

#: env var naming a plan: a path to a scenario/plan JSON, or inline JSON
PLAN_ENV = "DPTPU_CHAOS_PLAN"

SITES = (
    "trainer/batch_fetch",
    "trainer/train_step",
    "checkpoint/save",
    "checkpoint/restore",
    "serve/enqueue",
    "serve/drain",
    "serve/swap_params",
    "serve/aot_load",
    "device/put",
    "data/packed_read",
    "serve/session_append",
    "serve/route",
    "serve/health_poll",
)


def arm(plan: FaultPlan) -> FaultPlan:
    """Install ``plan`` process-wide; returns it."""
    global _PLAN
    _PLAN = plan
    return plan


def disarm() -> None:
    global _PLAN
    _PLAN = None


def armed() -> FaultPlan | None:
    """The armed plan, or None."""
    return _PLAN


def active_scenario() -> str | None:
    """The armed plan's name (bench records stamp this), or None."""
    plan = _PLAN
    return plan.name if plan is not None else None


@contextlib.contextmanager
def armed_plan(plan: FaultPlan):
    """Scope a plan to a ``with`` block (tests; the runner)."""
    prev = _PLAN
    arm(plan)
    try:
        yield plan
    finally:
        if prev is None:
            disarm()
        else:
            arm(prev)


def maybe_arm_from_env() -> FaultPlan | None:
    """Arm from ``DPTPU_CHAOS_PLAN`` if set (and nothing is armed yet):
    the value is a JSON file path or inline JSON holding either a bare
    plan ``{"seed", "faults"}`` or a scenario wrapper with a ``"plan"``
    key.  Returns the armed plan (new or pre-existing), None when unset.
    Called at the trainer's ``fit()`` and the serve worker's start — the
    env check is the only cost on the disabled path."""
    if _PLAN is not None:
        return _PLAN
    raw = os.environ.get(PLAN_ENV)
    if not raw:
        return None
    if raw.lstrip().startswith("{"):
        obj = json.loads(raw)
    else:
        with open(raw) as f:
            obj = json.load(f)
    if "plan" in obj and "faults" not in obj:  # scenario wrapper
        plan = dict(obj["plan"])
        plan.setdefault("name", obj.get("name", "env"))
        obj = plan
    return arm(FaultPlan.from_dict(obj))


def fire(site: str, payload=None, **ctx):
    """The hot-path hook every seam calls: with no plan armed this is one
    attribute check and a return; with a plan armed it delegates to
    :meth:`faults.FaultPlan.fire` (which may sleep, raise, signal,
    truncate ``ctx['path']``, or return a poisoned ``payload``)."""
    plan = _PLAN
    if plan is None:
        return payload
    return plan.fire(site, payload, **ctx)


class inject:
    """``fire`` as a context manager or decorator, for seams that wrap a
    block rather than transform a payload::

        with chaos_sites.inject("checkpoint/restore"):
            restored = mgr.restore(step, ...)

        @chaos_sites.inject("serve/enqueue")
        def submit(...): ...

    Fires on entry (context) / per call (decorator)."""

    def __init__(self, site: str, **ctx):
        self.site = site
        self.ctx = ctx

    def __enter__(self) -> "inject":
        fire(self.site, **self.ctx)
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            fire(self.site, **self.ctx)
            return fn(*args, **kwargs)

        return wrapper
