"""Chaos scenarios: a short fit or serve burst under a fault plan, with
the recovery invariants ASSERTED instead of assumed.

A scenario is small JSON::

    {"name": "preempt_mid_epoch",
     "mode": "fit_resume",                  # fit | fit_resume | serve
     "plan": {"seed": 0, "faults": [
         {"site": "trainer/train_step", "kind": "sigterm", "at": [2]}]},
     "overrides": {"epochs": 2, ...},       # trainer config overrides
     "params": {...},                       # mode-specific knobs
     "invariants": ["preempted_cleanly", ...]}

Modes:

* ``fit``        — one in-process :class:`train.Trainer` fit under the
  armed plan (the NaN-poisoning divergence-detection scenario);
* ``fit_resume`` — TWO child processes sharing a work dir: phase 1
  trains until the injected fault lands (SIGTERM preemption, or a
  truncation fault tearing the newest checkpoint), phase 2 is a fresh
  process resuming ``resume=auto`` — a real process death and restart,
  not a simulation, which also keeps the known in-process
  restore-then-refit XLA crash (tests/test_preemption.py) out of the
  runner's own process;
* ``serve``      — an in-process :class:`serve.InferenceService` burst
  under injected drain latency, asserting the service SHEDS (429/504)
  rather than crashing and serves again once the plan is disarmed;
* ``supervise``  — a REAL :class:`train.supervise.Supervisor` driving
  chaos child processes through SIGKILL crashes (``crash_loop``) or a
  SIGTERM storm (``preemption_storm``): every restart resumes from a
  committed checkpoint and the final trajectory completes the schedule.

Every run returns a report dict carrying per-invariant verdicts, the
``chaos_injected_total{site,kind}`` firings (child-process firings are
folded into this process's registry so one ``/metrics`` surface shows
the whole scenario), and the measured recovery time, observed into the
``chaos_recovery_seconds{scenario}`` histogram.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

from . import sites
from .faults import FaultPlan


class ChaosInvariantError(AssertionError):
    """One or more scenario invariants failed; the report is attached."""

    def __init__(self, report: dict):
        self.report = report
        failed = [f"{name}: {v['detail']}"
                  for name, v in report["invariants"].items()
                  if not v["ok"]]
        super().__init__(
            f"scenario {report['scenario']!r} failed "
            f"{len(failed)} invariant(s):\n  " + "\n  ".join(failed))


# --------------------------------------------------------------- scenarios

#: the tiny-but-real trainer config every train scenario builds on —
#: the shape tests/test_preemption.py uses (8-global-batch over the
#: 8-device CPU mesh, resnet18, 48px crops, sync saves, no val panels)
BASE_TRAIN_OVERRIDES = {
    "data.fake": True, "data.train_batch": 8, "data.val_batch": 2,
    "data.crop_size": [48, 48], "data.relax": 10, "data.area_thres": 0,
    "data.num_workers": 0, "model.backbone": "resnet18",
    "model.output_stride": 8, "optim.lr": 1e-4,
    "checkpoint.async_save": False, "epochs": 2, "eval_every": 0,
    "checkpoint.snapshot_every": 0, "log_every_steps": 1000,
}

SCENARIOS: dict[str, dict] = {
    # SIGTERM between steps, mid-epoch: graceful consensus stop -> final
    # checkpoint -> fresh-process restart -> exact resume.  The headline
    # acceptance scenario: zero optimizer steps lost or duplicated, and
    # the restored param tree is byte-identical to the saved one.
    "preempt_mid_epoch": {
        "name": "preempt_mid_epoch",
        "mode": "fit_resume",
        "plan": {"seed": 0, "faults": [
            {"site": "trainer/train_step", "kind": "sigterm", "at": [2]}]},
        "overrides": {"checkpoint.preempt_check_every": 3},
        "params": {"big_dataset": True},
        "invariants": ["preempted_cleanly", "stopped_mid_epoch",
                       "params_restored_exactly",
                       "zero_lost_or_duplicated_steps"],
    },
    # The truncation fault tears the NEWEST checkpoint's biggest file
    # after it committed; the resumed process must fall back to the last
    # COMPLETE step and still finish the schedule.
    "truncated_checkpoint": {
        "name": "truncated_checkpoint",
        "mode": "fit_resume",
        "plan": {"seed": 0, "faults": [
            {"site": "checkpoint/save", "kind": "truncate", "at": [2]}]},
        "overrides": {"checkpoint.snapshot_every": 1,
                      "checkpoint.keep_latest": 4},
        "params": {"big_dataset": False, "resume_epochs": None},
        "invariants": ["fell_back_past_torn_checkpoint",
                       "completed_after_fallback"],
    },
    # Injected drain latency saturates the batcher: deadlines expire
    # (504) and the bounded queue sheds at the door (429) — degradation,
    # not a crash — and the service recovers the moment the plan disarms.
    "serve_latency_shed": {
        "name": "serve_latency_shed",
        "mode": "serve",
        "plan": {"seed": 0, "faults": [
            {"site": "serve/drain", "kind": "latency", "delay_s": 0.25}]},
        "params": {"requests": 12, "clients": 4, "deadline_s": 0.05,
                   "queue_depth": 3, "max_batch": 2, "size": 64},
        "invariants": ["sheds_instead_of_crashing",
                       "recovers_after_disarm"],
    },
    # Hot-swap mid-burst against a session-serving service: a GOOD
    # checkpoint canaries and promotes while live sessions keep warm-
    # clicking (zero session-visible errors — the zero-downtime
    # invariant), then a NaN-poisoned checkpoint (the swap_params nan
    # fault, firing on the SECOND swap's param load) is caught by the
    # canary health check: its first poisoned output fails over to the
    # active params (the client still gets a finite mask) and the swap
    # rolls back.  Recovery = time from the rollback to a clean cold
    # click on the active generation.
    "hot_swap_under_load": {
        "name": "hot_swap_under_load",
        "mode": "serve_swap",
        "plan": {"seed": 0, "faults": [
            {"site": "serve/swap_params", "kind": "nan", "at": [2]}]},
        "params": {"sessions": 3, "warm_clicks": 4, "size": 64,
                   "max_batch": 4, "canary_fraction": 1.0},
        "invariants": ["zero_session_errors_during_swap",
                       "good_swap_promoted",
                       "sessions_survive_swap",
                       "bad_canary_rolled_back"],
    },
    # Restore under a DIFFERENT parallel plan than saved: a dp run is
    # preempted mid-epoch, and the fresh process resumes it with
    # parallel.strategy=dp_tp — the pod-resized-between-runs shape.
    # The sharding-aware restore must RESHARD (params byte-identical to
    # the saved ones after gather, layout the new plan's), announce the
    # plan crossing loudly (every checkpoint meta names the plan that
    # laid it out — the discriminator the trainer prints on), and the
    # resumed fit must complete the schedule under the new plan with
    # zero optimizer steps lost or duplicated.  Never garbage: digest
    # inequality anywhere in the chain fails params_restored_exactly.
    "plan_mismatch_restore": {
        "name": "plan_mismatch_restore",
        "mode": "fit_resume",
        "plan": {"seed": 0, "faults": [
            {"site": "trainer/train_step", "kind": "sigterm", "at": [2]}]},
        "overrides": {"checkpoint.preempt_check_every": 3},
        "params": {"big_dataset": True,
                   "resume_overrides": {"parallel.strategy": "dp_tp"}},
        "invariants": ["preempted_cleanly", "params_restored_exactly",
                       "resharded_across_plans",
                       "zero_lost_or_duplicated_steps"],
    },
    # NaN-poison the observed loss of one step WITH the step-health
    # sentinel armed: the run must RECOVER, not merely survive — the
    # sentinel's 'diverged' verdict rolls the trainer back to the last
    # committed checkpoint (the step-0 checkpoint fit() lands when the
    # sentinel is on), the poisoned window is quarantined to
    # run_dir/quarantine.jsonl, the replay skips it, and the schedule
    # still finishes with finite metrics — zero manual intervention.
    "nan_loss": {
        "name": "nan_loss",
        "mode": "fit",
        "plan": {"seed": 0, "faults": [
            {"site": "trainer/train_step", "kind": "nan", "at": [2]}]},
        "overrides": {"epochs": 1, "eval_every": 1, "log_every_steps": 1,
                      "debug_asserts": False, "sentinel.enabled": True},
        "params": {"big_dataset": True, "n_images": 16},
        "invariants": ["rollback_fired", "quarantine_written",
                       "fit_completes", "final_metrics_finite"],
    },
    # The pre-sentinel contract, pinned for back-compat: with
    # sentinel off the trainer's only response to a poisoned loss is
    # log-and-continue (train/nonfinite_steps), the fit completes and
    # final metrics stay finite because the state never saw the poison.
    "nan_loss_legacy": {
        "name": "nan_loss_legacy",
        "mode": "fit",
        "plan": {"seed": 0, "faults": [
            {"site": "trainer/train_step", "kind": "nan", "at": [1]}]},
        "overrides": {"epochs": 1, "eval_every": 1,
                      "debug_asserts": False},
        "invariants": ["nonfinite_steps_logged", "fit_completes",
                       "final_metrics_finite"],
    },
    # The headline self-healing scenario: NaN-poison strikes MID-RUN,
    # after real checkpoints have committed.  The sentinel rolls back to
    # the newest COMMITTED snapshot (not the initial state), quarantines
    # the poisoned window, replays past it, and the run finishes with
    # finite metrics — the "runs heal themselves" acceptance gate.
    "divergence_rollback": {
        "name": "divergence_rollback",
        "mode": "fit",
        "plan": {"seed": 0, "faults": [
            {"site": "trainer/train_step", "kind": "nan", "at": [10]}]},
        "overrides": {"epochs": 2, "eval_every": 1, "log_every_steps": 1,
                      "checkpoint.snapshot_every": 1,
                      "debug_asserts": False, "sentinel.enabled": True},
        "params": {"big_dataset": True},
        "invariants": ["rollback_fired", "rolled_back_to_committed",
                       "quarantine_written", "fit_completes",
                       "final_metrics_finite"],
    },
    # Sustained latency injected at the trainer's batch-fetch boundary
    # (the REAL input_wait seam): the feed governor (data/governor.py,
    # armed data.governor=auto) must climb its ladder unattended —
    # hot prefetch raises, then (flip ineligible: the device path is
    # already on, so the rung logs its recommendation) ARM data echoing
    # sized from the measured stall — and once the fault plan exhausts,
    # the windowed input_wait fraction must drain below
    # data.governor_target and the governor must DISARM echo with
    # hysteresis.  The whole decision sequence is asserted from
    # run_dir/governor.jsonl; recovery = the arm -> disarm span.
    "input_stall_recovery": {
        "name": "input_stall_recovery",
        "mode": "fit",
        "plan": {"seed": 0, "faults": [
            {"site": "trainer/batch_fetch", "kind": "latency",
             "delay_s": 0.5, "every": 1, "times": 14}]},
        "overrides": {"epochs": 4, "eval_every": 0, "log_every_steps": 1,
                      "data.governor": "auto",
                      "data.governor_target": 0.2,
                      "data.governor_window": 8, "data.max_echo": 4,
                      "data.device_augment": True,
                      "data.device_guidance": True},
        "params": {"big_dataset": True},
        "invariants": ["governor_armed_echo",
                       "stall_recovered_below_target",
                       "echo_disarmed_after_clear", "fit_completes"],
    },
    # SIGKILL mid-epoch, three times: no graceful stop, no final save —
    # the supervisor must restart each corpse, every restart must resume
    # from a COMMITTED checkpoint whose meta digest matches the restored
    # params byte-for-byte (checkpoint.digest), and the final trajectory
    # must complete the schedule.  The kill lands at per-process visit
    # 10 (> one epoch of steps), so every attempt first commits fresh
    # progress — which is exactly what keeps the supervisor's crash-loop
    # detector (3 identical no-progress crashes) from giving up.
    "crash_loop": {
        "name": "crash_loop",
        "mode": "supervise",
        "plan": {"seed": 0, "faults": [
            {"site": "trainer/train_step", "kind": "sigkill",
             "at": [10]}]},
        "overrides": {"epochs": 4, "eval_every": 0,
                      "checkpoint.snapshot_every": 1,
                      "checkpoint.digest": True},
        "params": {"big_dataset": True, "expected_crashes": 3,
                   "max_restarts": 8},
        "invariants": ["supervisor_recovered_each_crash",
                       "restored_digest_matches_committed",
                       "completed_schedule"],
    },
    # ELASTIC membership: the pod is reshaped under the run, three
    # times — 8 devices -> preempted down to 4 -> down to 2 -> hosts
    # re-added back to 8 — with a SIGTERM (the preempted-slice shape)
    # killing each generation mid-epoch.  The elastic supervisor
    # (train/elastic.py) must classify every exit topology_changed
    # (NEVER crashed/crash_loop: a shrink must not count toward
    # give-up), each restarted child re-resolves parallel.strategy=auto
    # against ITS device set, restores THROUGH the plan crossing with
    # the crossing announced, and across all four process generations
    # the digest chain is unbroken and not one optimizer step is lost
    # or duplicated — self-healing become self-scaling, no human in
    # the loop.
    "elastic_membership": {
        "name": "elastic_membership",
        "mode": "supervise",
        "plan": {"seed": 0, "faults": [
            {"site": "trainer/train_step", "kind": "sigterm",
             "at": [4]}]},
        "overrides": {"epochs": 2, "checkpoint.preempt_check_every": 1,
                      "checkpoint.digest": True,
                      "parallel.strategy": "auto"},
        "params": {"big_dataset": True, "expected_topology_changes": 3,
                   "device_schedule": [8, 4, 2, 8], "max_restarts": 8},
        "invariants": ["topology_changed_each_exit",
                       "replanned_each_change",
                       "plan_crossings_announced",
                       "exact_resume_chain",
                       "restored_digest_matches_committed",
                       "zero_lost_or_duplicated_steps_storm"],
    },
    # Torn pack: the packed data plane (data/packed.py) under bit rot.
    # Phase 1 — a bitflip fault at the data/packed_read seam corrupts
    # one record's bytes in flight: the per-record crc32 must trip and
    # the reader must raise the TYPED PackedRecordError naming the
    # record index — never serve a silent wrong sample.  Phase 2 — the
    # SAME record is then torn ON DISK, `dptpu-pack --verify` must flag
    # exactly the records sharing the torn blob, and a
    # data.pack_quarantine=[...] run must complete the schedule without
    # them.  Recovery = tear -> finished quarantined fit.
    "torn_pack": {
        "name": "torn_pack",
        "mode": "packed_fit",
        "plan": {"seed": 0, "faults": [
            {"site": "data/packed_read", "kind": "bitflip", "at": [3]}]},
        "overrides": {"epochs": 1, "eval_every": 0,
                      "log_every_steps": 1000},
        "params": {"n_images": 12},
        "invariants": ["packed_read_error_typed", "torn_record_detected",
                       "quarantined_run_completes"],
    },
    # Stale AOT executable cache (serve/aot.py): a replica boots warm
    # against a cache that rotted under it.  Three corruptions, each
    # the same contract — fall back LOUDLY to a fresh compile, serve
    # anyway, and never execute untrusted bytes: (1) a bitflip fault at
    # the serve/aot_load seam corrupts one entry's bytes in flight —
    # the per-entry crc32 must trip (typed AotCacheError) and that
    # program compiles fresh; (2) the same entry is then truncated ON
    # DISK — same refusal, and `dptpu-aot --verify` flags exactly the
    # bad entry; (3) the manifest's topology fingerprint is rewritten
    # to a foreign pod shape — every load is a typed miss NAMING the
    # mismatched key, the boot degrades to a full cold compile.  In
    # all three phases the serving masks stay bitwise identical to the
    # jit forward's (no silently-wrong executable, ever).
    "stale_aot_cache": {
        "name": "stale_aot_cache",
        "mode": "serve_aot",
        "plan": {"seed": 0, "faults": [
            {"site": "serve/aot_load", "kind": "bitflip", "at": [1]}]},
        "params": {"size": 64, "max_batch": 2},
        "invariants": ["corrupt_entry_falls_back",
                       "truncated_entry_falls_back",
                       "topology_mismatch_falls_back",
                       "serves_after_fallback"],
    },
    # Repeated SIGTERM across epochs: every wave stops gracefully
    # (consensus stop -> exact-resume checkpoint), the supervisor
    # restarts without backoff, and across the whole storm not one
    # optimizer step is lost or duplicated — the PR 5 invariant,
    # extended over N process generations.
    "preemption_storm": {
        "name": "preemption_storm",
        "mode": "supervise",
        "plan": {"seed": 0, "faults": [
            {"site": "trainer/train_step", "kind": "sigterm",
             "at": [4]}]},
        "overrides": {"epochs": 2, "checkpoint.preempt_check_every": 1},
        "params": {"big_dataset": True, "expected_preemptions": 3,
                   "max_restarts": 8},
        "invariants": ["preempted_each_wave", "exact_resume_chain",
                       "zero_lost_or_duplicated_steps_storm"],
    },
    # The closed production loop under poison: clicks stream through a
    # session-logging service while a nan fault at the
    # serve/session_append seam NaN-poisons two LOGGED examples (the
    # corrupted-annotation-pipeline failure — what the sink records,
    # never what the client sees); the flywheel then runs its guarded
    # incremental fit on the log — the step sentinel diverges, rolls the
    # fit back, and its quarantine ledger names the EXACT session
    # records (packed seek), which the flywheel quarantines durably;
    # the held fit never swaps, so the canary never promotes and the
    # fleet keeps serving generation 0 with zero session-visible
    # errors.  Recovery = the poisoned cycle -> clean clicks on the old
    # generation.
    "poisoned_flywheel": {
        "name": "poisoned_flywheel",
        "mode": "flywheel",
        "plan": {"seed": 0, "faults": [
            {"site": "serve/session_append", "kind": "nan",
             "at": [4, 9]}]},
        "overrides": {"log_every_steps": 1, "debug_asserts": False,
                      "sentinel.max_rollbacks": 3},
        "params": {"size": 48, "clicks": 16, "max_batch": 4},
        "invariants": ["poisoned_records_quarantined",
                       "canary_never_promoted",
                       "serves_old_generation_zero_errors"],
    },
    # Replica death under interactive load: a 3-replica LOCAL fleet
    # (serve/fleet.py spawns real dptpu-serve children) takes a warm
    # click burst from sessions pinned — by the ring's process-
    # independent blake2b hash — to every replica, while a sigkill
    # fault at the serve/drain seam (armed via DPTPU_CHAOS_PLAN in
    # exactly ONE replica's first boot) SIGKILLs that replica mid-
    # burst.  What must hold: clients see ZERO untyped 5xx (the front's
    # one-shot failover + the typed shed taxonomy absorb the death);
    # the dead replica's sessions rehash and complete on their new
    # replica (one counted re-encode, not an error); the supervisor
    # respawns the slot and the ring CONVERGES back to full count (the
    # respawn reuses its slot id, so those sessions come home); and the
    # kill->rejoin span lands in chaos_recovery_seconds{scenario},
    # measured from the fleet's own flight-recorder events.
    "replica_kill_under_load": {
        "name": "replica_kill_under_load",
        "mode": "fleet",
        "plan": {"seed": 0, "faults": [
            # visit 4 of the victim's serve/drain (one visit per drained
            # batch): past its 2 pinned cold clicks, inside the burst
            {"site": "serve/drain", "kind": "sigkill", "at": [4]}]},
        "params": {"replicas": 3, "sessions_per_replica": 2,
                   "warm_clicks": 4, "size": 48, "max_batch": 4,
                   "poll_interval_s": 0.25},
        "invariants": ["zero_untyped_client_errors",
                       "rehashed_sessions_reencode",
                       "ring_converges_full_count",
                       "recovery_recorded"],
    },
}


def load_scenario(name_or_path: str) -> dict:
    """A builtin scenario by name, or a JSON file by path."""
    if name_or_path in SCENARIOS:
        return json.loads(json.dumps(SCENARIOS[name_or_path]))  # deep copy
    with open(name_or_path) as f:
        sc = json.load(f)
    sc.setdefault("name", os.path.splitext(
        os.path.basename(name_or_path))[0])
    return sc


# ----------------------------------------------------------------- helpers

def param_digest(tree) -> str:
    """Order-stable sha256 over a param tree's raw bytes — the
    restored-vs-saved equality check that works across processes.
    Canonical implementation lives in train/checkpoint.py (the
    ``checkpoint.digest`` config stamps the same digest into save
    metas, which is what makes the crash_loop scenario's continuity
    check possible across SIGKILLed processes)."""
    from ..train.checkpoint import param_digest as _param_digest

    return _param_digest(tree)


class RecordingWriter:
    """MetricWriter that keeps every scalar in memory — the invariant
    checks read what the trainer LOGGED, not internals."""

    def __init__(self):
        self.scalars_seen: list[tuple[int, dict]] = []

    def scalars(self, metrics, step):
        self.scalars_seen.append((int(step), dict(metrics)))

    def figure(self, name, fig, step):
        pass

    def hparams(self, params):
        pass

    def flush(self):
        pass

    def close(self):
        pass

    def last(self, key):
        for _step, m in reversed(self.scalars_seen):
            if key in m:
                return m[key]
        return None

    def total(self, key):
        """Sum of every logged value of ``key`` (0 when never logged) —
        the right read for per-epoch counts like train/nonfinite_steps,
        which the trainer emits once per epoch with that epoch's tally."""
        return sum(m[key] for _step, m in self.scalars_seen if key in m)


def _maybe_big_dataset(params: dict, overrides: dict,
                       work_dir: str) -> dict:
    """``params.big_dataset``: several batches per epoch, so something
    can strike (and be quarantined / resumed past) MID-epoch — the
    trainer's own fake fixture is ~1 batch.  ``params.n_images`` sizes
    it (default 32 ≈ 7 batches/epoch; the tier-1 nan_loss smoke uses 16
    to stay inside the suite budget)."""
    if params.get("big_dataset"):
        from ..data import make_fake_voc

        overrides = dict(overrides)
        overrides["data.root"] = make_fake_voc(
            os.path.join(work_dir, "voc"),
            n_images=int(params.get("n_images", 32)), size=(96, 128),
            n_val=2, seed=0)
    return overrides


def _read_jsonl(run_dir: str, name: str) -> list[dict]:
    """Parsed records of a run-dir JSONL ledger (empty when none)."""
    records = []
    try:
        with open(os.path.join(run_dir, name)) as f:
            for line in f:
                if line.strip():
                    records.append(json.loads(line))
    except OSError:
        pass
    return records


def _read_governor(run_dir: str) -> list[dict]:
    """Parsed ``governor.jsonl`` decision records (empty when none)."""
    return _read_jsonl(run_dir, "governor.jsonl")


def _governor_recovery_s(records: list[dict]) -> float | None:
    """arm -> disarm wall-clock from the governor ledger (the
    input_stall_recovery scenario's recovery measure): time from the
    first applied escalation past rung 1 to the disarm that closed the
    episode.  None when the ledger holds no such pair."""
    armed_ts = None
    for r in records:
        if r.get("action") in ("arm_echo", "flip_device_path") \
                and r.get("applied") and armed_ts is None:
            armed_ts = r.get("ts")
        if r.get("action") == "disarm_echo" and r.get("applied") \
                and armed_ts is not None:
            return max(0.0, float(r["ts"]) - float(armed_ts))
    return None


def _read_quarantine(run_dir: str) -> list[dict]:
    """Parsed ``quarantine.jsonl`` records (empty when none written)."""
    return _read_jsonl(run_dir, "quarantine.jsonl")


def _build_cfg(overrides: dict, work_dir: str):
    from ..train import Config, apply_overrides

    merged = dict(BASE_TRAIN_OVERRIDES)
    merged.update(overrides or {})
    merged["work_dir"] = work_dir
    cfg = apply_overrides(Config(), merged)
    # JSON carries lists; crop_size is a tuple in the dataclass contract
    import dataclasses

    return dataclasses.replace(
        cfg, data=dataclasses.replace(
            cfg.data, crop_size=tuple(cfg.data.crop_size)))


def _book_child_firings(report: dict) -> None:
    """Fold a child process's chaos_injected_total into THIS process's
    registry, so the runner's one metrics surface shows every firing of
    the scenario regardless of which process it happened in."""
    from ..telemetry import get_registry

    for key, n in (report.get("chaos_injected_total") or {}).items():
        site, _, kind = key.partition("|")
        get_registry().counter(
            "chaos_injected_total",
            "Deterministic fault-injection firings (chaos/)",
            labels={"site": site, "kind": kind}).inc(n)


def _observe_recovery(scenario: str, seconds: float) -> None:
    from ..telemetry import get_registry

    get_registry().histogram(
        "chaos_recovery_seconds",
        "Time from injected failure to recovered service/trainer",
        labels={"scenario": scenario}).observe(seconds)


# ------------------------------------------------------------- child phase

def child_fit(spec_path: str) -> int:
    """One training phase in a throwaway process (``dptpu-chaos --child``):
    build the config, arm the plan (if any), fit, report JSON.  The
    parent interprets; this side only measures."""
    with open(spec_path) as f:
        spec = json.load(f)
    from ..backend_health import enable_compile_cache

    enable_compile_cache()
    from ..train import Trainer

    plan = None
    if spec.get("plan"):
        plan = sites.arm(FaultPlan.from_dict(spec["plan"]))
    cfg = _build_cfg(spec.get("overrides") or {}, spec["work_dir"])
    t0 = time.perf_counter()
    tr = Trainer(cfg)
    construct_s = time.perf_counter() - t0
    report: dict = {
        "phase": spec.get("phase", "fit"),
        "run_dir": tr.run_dir,
        "nb": len(tr.train_loader),
        "construct_seconds": round(construct_s, 4),
        "restored_step": int(tr.state.step),
        "start_epoch": tr.start_epoch,
        "resume_start_batch": tr._resume_start_batch,
        "restore_fallback": list(getattr(tr, "resume_fallback_steps", [])),
        "param_digest_at_restore": param_digest(tr.state.params),
        # the digest the restored checkpoint's meta CLAIMS
        # (checkpoint.digest runs; None otherwise) — byte-identical
        # restore is provable even when this process is later SIGKILLed
        "restored_meta_digest": tr.resume_meta.get("param_digest"),
        # the parallel plan THIS process resolved, and the plan the
        # restored checkpoint's meta says laid the state out — the
        # plan_mismatch_restore scenario's evidence pair: differing is
        # fine (sharding-aware restore resharded), but only KNOWINGLY
        "plan": tr.plan.block(),
        "restored_meta_plan": tr.resume_meta.get("plan"),
        # elastic evidence: did the trainer ANNOUNCE a plan/topology
        # crossing at restore, and how many devices did this process
        # actually see (elastic_membership asserts both; in the
        # PREFLIGHT sidecar, because later generations get killed)
        "plan_crossing": bool(tr.resume_plan_crossing),
        "n_devices": int(tr.mesh.devices.size),
    }
    # Preflight sidecar, BEFORE fit: a supervised child that dies
    # mid-fit (sigkill faults) still leaves its restore evidence for
    # the parent's continuity invariants.
    with open(spec["report"] + ".pre", "w") as f:
        json.dump(report, f)
    history = tr.fit()
    report.update({
        "final_step": int(tr.state.step),
        "preempted": bool(history.get("preempted")),
        "epochs_recorded": len(history["train_loss"]),
        "latest_step": tr.ckpt.latest_step(),
        "saved_steps": tr.ckpt.all_steps(),
        "param_digest": param_digest(tr.state.params),
        "recovery": history.get("recovery"),
        "quarantine": _read_quarantine(tr.run_dir),
    })
    tr.close()
    if plan is not None:
        report["chaos_injected_total"] = {
            f"{site}|{kind}": n
            for (site, kind), n in plan.injected_total().items()}
        sites.disarm()
    with open(spec["report"], "w") as f:
        json.dump(report, f)
    return 0


def _run_child(spec: dict, tag: str, scratch: str, timeout_s: float = 600
               ) -> dict:
    spec = dict(spec)
    spec["report"] = os.path.join(scratch, f"report_{tag}.json")
    spec_path = os.path.join(scratch, f"spec_{tag}.json")
    with open(spec_path, "w") as f:
        json.dump(spec, f)
    from ..backend_health import pin_cpu8_topology

    # the canonical tier-1 topology unless the caller pinned another
    env = pin_cpu8_topology(dict(os.environ))
    # the child's plan rides in the spec file; an inherited env plan
    # (the operator ran dptpu-chaos WITH DPTPU_CHAOS_PLAN exported)
    # would re-arm inside the recovery phase that must run clean
    env.pop(sites.PLAN_ENV, None)
    r = subprocess.run(
        [sys.executable, "-m", "distributedpytorch_tpu.chaos",
         "--child", spec_path],
        capture_output=True, text=True, timeout=timeout_s,
        cwd=os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))), env=env)
    if r.returncode != 0 or not os.path.exists(spec["report"]):
        raise RuntimeError(
            f"chaos child phase {tag!r} exited {r.returncode}:\n"
            f"{r.stdout[-2000:]}\n{r.stderr[-2000:]}")
    with open(spec["report"]) as f:
        report = json.load(f)
    _book_child_firings(report)
    return report


# ------------------------------------------------------------------ modes

def _run_fit_resume(sc: dict, work_dir: str) -> dict:
    params = sc.get("params") or {}
    overrides = _maybe_big_dataset(params, dict(sc.get("overrides") or {}),
                                   work_dir)
    p1 = _run_child({"phase": "fault", "plan": sc.get("plan"),
                     "overrides": overrides, "work_dir": work_dir},
                    "fault", work_dir)
    resume_overrides = dict(overrides)
    resume_overrides["resume"] = "auto"
    if params.get("resume_epochs"):
        resume_overrides["epochs"] = params["resume_epochs"]
    # phase-2-ONLY overrides: the resumed process's config may differ
    # from the saver's (plan_mismatch_restore resumes a dp run under
    # parallel.strategy=dp_tp — the pod-resized-between-runs shape)
    resume_overrides.update(params.get("resume_overrides") or {})
    p2 = _run_child({"phase": "resume", "plan": None,
                     "overrides": resume_overrides, "work_dir": work_dir},
                    "resume", work_dir)
    # recovery = time to a restored, ready-to-train trainer (the resume
    # child's construction, restore included) — NOT the child's whole
    # wall-clock, which is dominated by the scheduled training it then
    # performs and would make the histogram read as throughput
    recovery_s = p2["construct_seconds"]
    _observe_recovery(sc["name"], recovery_s)
    return {"phases": {"fault": p1, "resume": p2},
            "recovery_s": round(recovery_s, 3)}


def _run_fit(sc: dict, work_dir: str) -> dict:
    from ..train import Trainer

    plan = FaultPlan.from_dict(dict(sc.get("plan") or {},
                                    name=sc["name"]))
    writer = RecordingWriter()
    overrides = _maybe_big_dataset(sc.get("params") or {},
                                   dict(sc.get("overrides") or {}),
                                   work_dir)
    cfg = _build_cfg(overrides, work_dir)
    with sites.armed_plan(plan):
        tr = Trainer(cfg, writers=writer)
        nb = len(tr.train_loader)
        t0 = time.perf_counter()
        history = tr.fit()
        fit_s = time.perf_counter() - t0
        tr.close()
    # sentinel scenarios: recovery = the measured rollback restore
    # time(s); governor scenarios: the arm -> disarm span from the
    # decision ledger.  Neither = the whole fit (a fit that mostly
    # trains healthily would otherwise read as slow recovery).
    governor_records = _read_governor(tr.run_dir)
    rec = history.get("recovery") or {}
    recovery_s = rec.get("recovery_p50_s")
    if recovery_s is None:
        recovery_s = _governor_recovery_s(governor_records)
    _observe_recovery(sc["name"],
                      fit_s if recovery_s is None else recovery_s)
    return {"phases": {"fit": {
        "nb": nb,
        "final_step": int(tr.state.step),
        "epochs_recorded": len(history["train_loss"]),
        "val": history["val"],
        "nonfinite_steps_logged": writer.total("train/nonfinite_steps"),
        "preempted": bool(history.get("preempted")),
        "recovery": history.get("recovery"),
        "quarantine": _read_quarantine(tr.run_dir),
        "feed": history.get("feed"),
        "governor": governor_records,
    }}, "recovery_s": round(fit_s if recovery_s is None else recovery_s, 3),
        "firings": plan.injected_total()}


def _run_packed_fit(sc: dict, work_dir: str) -> dict:
    """``torn_pack``: fake VOC is packed; a bitflip at the
    ``data/packed_read`` seam must surface as the typed
    ``PackedRecordError``; the record is then torn on disk, ``--verify``
    flags it, and a quarantine-by-index run completes (see the scenario
    comment)."""
    from ..data import VOCInstanceSegmentation, make_fake_voc
    from ..data import packed as packed_lib
    from ..train import Trainer

    params = sc.get("params") or {}
    root = make_fake_voc(os.path.join(work_dir, "voc"),
                         n_images=int(params.get("n_images", 12)),
                         size=(96, 128), n_val=2, seed=0)
    pack_root = os.path.join(work_dir, "packs")
    for split in ("train", "val"):
        src = VOCInstanceSegmentation(root, split=split, preprocess=True,
                                      area_thres=0)
        packed_lib.pack_dataset(
            src,
            packed_lib.pack_dir_path(pack_root, "voc", "instance",
                                     [split]),
            dataset_name="voc", splits=[split], area_thres=0)
    overrides = dict(sc.get("overrides") or {})
    overrides.update({"data.root": root, "data.source": "packed",
                      "data.pack_path": pack_root})
    plan = FaultPlan.from_dict(dict(sc.get("plan") or {},
                                    name=sc["name"]))
    typed_error = bad_index = None
    error_msg = ""
    cfg = _build_cfg(overrides, work_dir)
    with sites.armed_plan(plan):
        tr = Trainer(cfg, writers=RecordingWriter())
        nb_full = len(tr.train_loader)
        try:
            tr.fit()
        except packed_lib.PackedRecordError as e:
            typed_error = type(e).__name__
            bad_index = int(e.index)
            error_msg = str(e)
        finally:
            tr.close()

    # tear the SAME record on disk and recover by quarantine-by-index
    train_pack = packed_lib.pack_dir_path(pack_root, "voc", "instance",
                                          ["train"])
    verify_bad: list[int] = []
    phase2: dict = {}
    t0 = time.perf_counter()
    if bad_index is not None:
        packed_lib.corrupt_record(train_pack, bad_index)
        verify_bad = packed_lib.verify_pack(train_pack)
        cfg2 = _build_cfg(
            dict(overrides, **{"data.pack_quarantine": verify_bad}),
            work_dir)
        tr2 = Trainer(cfg2, writers=RecordingWriter())
        hist2 = tr2.fit()
        tr2.close()
        phase2 = {
            "nb_quarantined": len(tr2.train_loader),
            "epochs_recorded": len(hist2["train_loss"]),
            "preempted": bool(hist2.get("preempted")),
            "final_step": int(tr2.state.step),
        }
    recovery_s = time.perf_counter() - t0
    _observe_recovery(sc["name"], recovery_s)
    return {"phases": {"packed_fit": dict({
        "typed_error": typed_error,
        "bad_index": bad_index,
        "error_names_index": (bad_index is not None
                              and f"record {bad_index} " in error_msg),
        "verify_bad": verify_bad,
        "nb_full": nb_full,
    }, **phase2)}, "recovery_s": round(recovery_s, 3),
        "firings": plan.injected_total()}


def _run_serve(sc: dict, work_dir: str) -> dict:
    import threading

    import jax
    import numpy as np
    import optax

    from ..models import build_model
    from ..parallel import create_train_state
    from ..predict import Predictor
    from ..serve import InferenceService
    from ..serve.service import DeadlineExceededError, QueueFullError

    p = dict(sc.get("params") or {})
    size = int(p.get("size", 64))
    plan = FaultPlan.from_dict(dict(sc.get("plan") or {},
                                    name=sc["name"]))
    model = build_model("danet", nclass=1, backbone="resnet18",
                        output_stride=8)
    state = create_train_state(jax.random.PRNGKey(0), model,
                               optax.sgd(1e-3), (1, size, size, 4))
    predictor = Predictor(model, state.params, state.batch_stats,
                          resolution=(size, size), relax=20)
    r = np.random.RandomState(0)
    image = r.randint(0, 256, (size, size, 3)).astype(np.uint8)
    q, m = size // 4, size // 2
    points = np.array([[q, m], [size - q, m], [m, q], [m, size - q]],
                      np.float64)

    svc = InferenceService(predictor, max_batch=int(p.get("max_batch", 2)),
                           queue_depth=int(p.get("queue_depth", 3)),
                           max_wait_s=0.0)
    svc.warmup()  # compiles off the fault path — chaos tests recovery,
    #               not cold-start XLA time
    outcomes = {"completed": 0, "shed_queue_full": 0, "shed_deadline": 0,
                "other_error": 0}
    lock = threading.Lock()

    def count(key):
        with lock:
            outcomes[key] += 1

    n = int(p.get("requests", 12))
    deadline_s = float(p.get("deadline_s", 0.05))

    def client(k):
        for _ in range(n // int(p.get("clients", 4))):
            try:
                fut = svc.submit(image, points, deadline_s=deadline_s)
                fut.result(timeout=60)
                count("completed")
            except QueueFullError:
                count("shed_queue_full")
            except DeadlineExceededError:
                count("shed_deadline")
            except Exception:
                count("other_error")

    with svc, sites.armed_plan(plan):
        threads = [threading.Thread(target=client, args=(k,))
                   for k in range(int(p.get("clients", 4)))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        health_under_fault = svc.health()
        # plan disarmed here; the service must serve again IMMEDIATELY —
        # the recovery the scenario exists to pin
        sites.disarm()
        t0 = time.perf_counter()
        try:
            svc.predict(image, points, timeout=60)
            recovered = True
        except Exception:
            recovered = False
        recovery_s = time.perf_counter() - t0
    _observe_recovery(sc["name"], recovery_s)
    return {"phases": {"serve": {
        "outcomes": outcomes,
        "submitted": (n // int(p.get("clients", 4)))
        * int(p.get("clients", 4)),
        "health_under_fault": {
            k: health_under_fault[k]
            for k in ("running", "state", "unhealthy_reason")},
        "recovered_after_disarm": recovered,
        "stats": svc.metrics.snapshot(),
    }}, "recovery_s": round(recovery_s, 3),
        "firings": plan.injected_total()}


def _run_serve_aot(sc: dict, work_dir: str) -> dict:
    """stale_aot_cache: a warm-boot cache rots three ways — in-flight
    bitflip, on-disk truncation, topology-mismatched manifest — and
    every boot falls back loudly, serves, and stays bitwise-correct
    (see SCENARIOS)."""
    import jax
    import numpy as np
    import optax

    from ..models import build_model
    from ..parallel import create_train_state
    from ..predict import Predictor
    from ..serve import InferenceService
    from ..serve import aot as aot_lib
    from ..train.checkpoint import atomic_write_json

    p = dict(sc.get("params") or {})
    size = int(p.get("size", 64))
    max_batch = int(p.get("max_batch", 2))
    plan = FaultPlan.from_dict(dict(sc.get("plan") or {},
                                    name=sc["name"]))
    model = build_model("danet", nclass=1, backbone="resnet18",
                        output_stride=8)
    state = create_train_state(jax.random.PRNGKey(0), model,
                               optax.sgd(1e-3), (1, size, size, 4))

    def make_predictor():
        # one predictor per boot: each service's AOT install table and
        # jit ladder are its own, like separate replica processes
        return Predictor(model, state.params, state.batch_stats,
                         resolution=(size, size), relax=20)

    cache_dir = os.path.join(work_dir, "aot")
    cache = aot_lib.AotCache(cache_dir)
    built = cache.build(make_predictor(), tuple(
        b for b in (1, 2, 4, 8) if b <= max_batch))
    r = np.random.RandomState(0)
    image = r.randint(0, 256, (size, size, 3)).astype(np.uint8)
    q, m = size // 4, size // 2
    points = np.array([[q, m], [size - q, m], [m, q], [m, size - q]],
                      np.float64)
    # ground truth from the ordinary jit forward — serialization
    # round-trips AND compile fallbacks must both reproduce it bitwise
    expected = make_predictor().predict(image, points)

    def boot_and_serve(tag: str) -> dict:
        svc = InferenceService(make_predictor(), max_batch=max_batch,
                               queue_depth=16, max_wait_s=0.0,
                               aot_cache=aot_lib.AotCache(cache_dir))
        warm = svc.warmup()
        with svc:
            try:
                mask = svc.predict(image, points, timeout=120)
                served = bool(np.isfinite(mask).all())
                bitwise = bool(np.array_equal(mask, expected))
            except Exception as e:  # noqa: BLE001 — reported, asserted
                served = bitwise = False
                mask = None
                warm = dict(warm, error=f"{type(e).__name__}: {e}")
        return {"tag": tag, "warmup": warm, "served_ok": served,
                "bitwise_equal": bitwise,
                "fallbacks": sorted({e["fallback"]
                                     for e in warm["programs"]
                                     if e.get("fallback")})}

    t0 = time.perf_counter()
    # phase 1: in-flight bitflip (the armed plan fires on the FIRST
    # serve/aot_load visit) — crc refuses, that program compiles fresh
    with sites.armed_plan(plan):
        flipped = boot_and_serve("bitflip_in_flight")

    # phase 2: the first entry torn ON DISK — same refusal from a clean
    # read path, and --verify's sweep must name exactly the bad entry
    man = cache.manifest()
    victim = sorted(man["entries"])[0]
    victim_path = os.path.join(cache_dir, man["entries"][victim]["file"])
    from .faults import truncate_file

    truncate_file(victim_path, fraction=0.5)
    verify_report = cache.verify()
    truncated = boot_and_serve("truncated_on_disk")

    # phase 3: topology-mismatched manifest — a cache built for a
    # different pod shape misses loudly on EVERY entry (the message
    # names the key), and the boot degrades to a full cold compile
    man2 = cache.manifest()
    man2["fingerprint"]["topology"] = "tpu:256/p32"
    atomic_write_json(cache.manifest_path(), man2)
    mismatched = boot_and_serve("topology_mismatch")
    recovery_s = time.perf_counter() - t0
    _observe_recovery(sc["name"], recovery_s)
    return {"phases": {"serve_aot": {
        "built": built["programs"],
        "bitflip": flipped,
        "verify_report": {k: verify_report[k]
                          for k in ("entries", "bad", "missing")},
        "victim": victim,
        "truncated": truncated,
        "mismatch": mismatched,
    }}, "recovery_s": round(recovery_s, 3),
        "firings": plan.injected_total()}


def _run_serve_swap(sc: dict, work_dir: str) -> dict:
    """hot_swap_under_load: promote a good checkpoint and roll back a
    poisoned one, under live session traffic (see SCENARIOS)."""
    import threading

    import jax
    import numpy as np
    import optax

    from ..models import build_model
    from ..parallel import create_train_state
    from ..predict import Predictor
    from ..serve import InferenceService
    from ..serve.swap import load_swap_predictor

    p = dict(sc.get("params") or {})
    size = int(p.get("size", 64))
    n_sessions = int(p.get("sessions", 3))
    warm_clicks = int(p.get("warm_clicks", 4))
    plan = FaultPlan.from_dict(dict(sc.get("plan") or {},
                                    name=sc["name"]))
    model = build_model("danet", nclass=1, backbone="resnet18",
                        output_stride=8, guidance_inject="head")
    tx = optax.sgd(1e-3)
    state = create_train_state(jax.random.PRNGKey(0), model, tx,
                               (1, size, size, 4))
    predictor = Predictor(model, state.params, state.batch_stats,
                          resolution=(size, size), relax=20)
    good = create_train_state(jax.random.PRNGKey(7), model, tx,
                              (1, size, size, 4))
    bad = create_train_state(jax.random.PRNGKey(9), model, tx,
                             (1, size, size, 4))
    r = np.random.RandomState(0)
    image = r.randint(0, 256, (size, size, 3)).astype(np.uint8)
    q, m = size // 4, size // 2
    points = np.array([[q, m], [size - q, m], [m, q], [m, size - q]],
                      np.float64)

    svc = InferenceService(predictor,
                           max_batch=int(p.get("max_batch", 4)),
                           queue_depth=64, max_wait_s=0.0)
    svc.warmup()
    outcomes = {"completed": 0, "shed": 0, "other_error": 0}
    lock = threading.Lock()

    def count(key):
        with lock:
            outcomes[key] += 1

    def click(session_id, pts):
        from ..serve.service import (
            DeadlineExceededError,
            QueueFullError,
        )
        try:
            mask = svc.predict(image, pts, timeout=120,
                               session_id=session_id)
            count("completed" if np.isfinite(mask).all()
                  else "other_error")
        except (QueueFullError, DeadlineExceededError):
            count("shed")
        except Exception:
            count("other_error")

    with svc, sites.armed_plan(plan):
        # live sessions, established BEFORE the swap (1 cold click each)
        for s in range(n_sessions):
            click(f"pre-{s}", points)

        # the burst: every session warm-clicks concurrently...
        threads = [
            threading.Thread(
                target=lambda sid=f"pre-{s}": [
                    click(sid, points + (k % 3))
                    for k in range(warm_clicks)])
            for s in range(n_sessions)]
        for t in threads:
            t.start()
        # ...and the GOOD swap lands mid-burst (swap_params visit 1:
        # no fault), canarying 100% of new sessions
        pred_good = load_swap_predictor(predictor, good.params,
                                        good.batch_stats)
        gen_good = svc.swap(
            pred_good, label="good",
            canary_fraction=float(p.get("canary_fraction", 1.0)))
        click("canary-0", points)      # canary traffic
        for t in threads:
            t.join()
        outcomes_during_swap = dict(outcomes)
        svc.promote()
        # sessions established before the swap must still warm-hit their
        # cached features (served by the now-draining generation 0)
        hits_before = svc.health()["sessions"]["hits"]
        click("pre-0", points + 1)
        survived = (svc.health()["sessions"]["hits"] == hits_before + 1)

        # the BAD swap: swap_params visit 2 NaN-poisons the param tree;
        # the canary's first output rolls it back and fails over, so the
        # client still sees a finite mask
        pred_bad = load_swap_predictor(predictor, bad.params,
                                       bad.batch_stats)
        svc.swap(pred_bad, label="bad", canary_fraction=1.0)
        t0 = time.perf_counter()
        click("victim-0", points)
        swap_state = svc.health()["swap"]
        # recovery: the service serves a clean cold click on the active
        # generation immediately after the rollback
        try:
            mask = svc.predict(image, points, timeout=120,
                               session_id="post-rollback")
            recovered = bool(np.isfinite(mask).all())
        except Exception:
            recovered = False
        recovery_s = time.perf_counter() - t0
        final_outcomes = dict(outcomes)
        sessions_snap = svc.health()["sessions"]
    _observe_recovery(sc["name"], recovery_s)
    bad_gens = [g for g in swap_state["generations"]
                if g["label"] == "bad"]
    return {"phases": {"serve_swap": {
        "outcomes_during_swap": outcomes_during_swap,
        "outcomes": final_outcomes,
        # clicks routed through the counting wrapper: per-session cold +
        # warm bursts, the canary click, the post-promote warm check,
        # and the bad-canary victim (the post-rollback recovery probe
        # reports via recovered_after_rollback instead)
        "submitted": n_sessions * (1 + warm_clicks) + 3,
        "good_generation": gen_good,
        "swap_state": swap_state,
        "bad_generation": bad_gens[0] if bad_gens else None,
        "old_sessions_warm_after_promote": survived,
        "recovered_after_rollback": recovered,
        "sessions": sessions_snap,
        "stats": svc.metrics.snapshot(),
    }}, "recovery_s": round(recovery_s, 3),
        "firings": plan.injected_total()}


def _run_flywheel(sc: dict, work_dir: str) -> dict:
    """poisoned_flywheel: serve -> session log -> guarded fit -> held
    swap (see SCENARIOS).  The nan fault poisons what the sink LOGS,
    never what the client sees — containment is the flywheel's burden."""
    import jax
    import numpy as np
    import optax

    from ..data.sessions import SessionLogDataset
    from ..models import build_model
    from ..parallel import create_train_state
    from ..predict import Predictor
    from ..serve import InferenceService
    from ..serve.session_log import SessionLogSink
    from ..train.continuous import Flywheel

    p = dict(sc.get("params") or {})
    size = int(p.get("size", 48))
    n_clicks = int(p.get("clicks", 16))
    plan = FaultPlan.from_dict(dict(sc.get("plan") or {},
                                    name=sc["name"]))
    model = build_model("danet", nclass=1, backbone="resnet18",
                        output_stride=8, guidance_inject="head")
    state = create_train_state(jax.random.PRNGKey(0), model,
                               optax.sgd(1e-3), (1, size, size, 4))
    predictor = Predictor(model, state.params, state.batch_stats,
                          resolution=(size, size), relax=10)
    log_dir = os.path.join(work_dir, "session_log")
    # the sink is built here (not via the service's path shorthand) so
    # the runner can commit meta at phase boundaries deterministically
    # instead of racing the worker's 1 Hz housekeeping flush
    sink = SessionLogSink(log_dir, resolution=predictor.resolution,
                          guidance=predictor.guidance,
                          alpha=predictor.alpha, relax=predictor.relax,
                          zero_pad=predictor.zero_pad)
    svc = InferenceService(predictor,
                           max_batch=int(p.get("max_batch", 4)),
                           queue_depth=64, max_wait_s=0.0,
                           session_log=sink)
    svc.warmup()
    r = np.random.RandomState(0)
    outcomes = {"completed": 0, "failed": 0}

    def click(session_id, image, pts):
        try:
            mask = svc.predict(image, pts, timeout=120,
                               session_id=session_id)
            ok = bool(np.isfinite(mask).all())
        except Exception:  # noqa: BLE001 — any failure is the tally's
            ok = False
        outcomes["completed" if ok else "failed"] += 1

    def spread_points(i):
        q, m = size // 4, size // 2
        pts = np.array([[q, m], [size - q, m], [m, q], [m, size - q]],
                       np.float64)
        return np.clip(pts + (i % 3), 0, size - 1)

    with svc, sites.armed_plan(plan):
        # phase 1: live traffic — each click a distinct image, so every
        # accepted example lands in the log (dedup never trips), and
        # the armed nan faults poison their scheduled appends
        for i in range(n_clicks):
            image = r.randint(0, 256, (size, size, 3)).astype(np.uint8)
            click(f"s{i}", image, spread_points(i))
        # the worker offers AFTER resolving each future (a sink hiccup
        # must never fail a request), so the last click's append may
        # still be in flight when predict() returns — drain the tally
        # before committing meta, or the fit would train on n-1 records
        deadline = time.perf_counter() + 30.0
        while time.perf_counter() < deadline:
            snap = sink.snapshot()
            if (snap["appended"] + snap["deduped"]
                    + sum(snap["dropped"].values())) >= n_clicks:
                break
            time.sleep(0.02)
        sink.flush(force=True)  # commit meta: readers trust counts only
        outcomes_serving = dict(outcomes)
        sink_snap = sink.snapshot()

        # ground truth for the invariants: which COMMITTED records
        # actually carry the poison (NaN crop bytes on disk)
        ds = SessionLogDataset(log_dir)
        poisoned = [ds.record_index(i) for i in range(len(ds))
                    if not np.isfinite(
                        ds.seek(i, read=True)["image"]).all()]

        # phase 2: one flywheel cycle — guarded fit on the poisoned
        # log; the sentinel must roll back and the cycle must HOLD
        cfg = _build_cfg(dict(sc.get("overrides") or {}), work_dir)
        fw = Flywheel(log_dir, cfg, os.path.join(work_dir, "flywheel"),
                      service=svc, min_new_records=1, fit_epochs=1)
        cycle = fw.poll()

        # phase 3: the fleet must still be serving generation 0 —
        # clean clicks, zero errors, no promotion ever attempted
        t0 = time.perf_counter()
        for i in range(3):
            image = r.randint(0, 256, (size, size, 3)).astype(np.uint8)
            click(f"post{i}", image, spread_points(i))
        recovery_s = time.perf_counter() - t0
        swap_state = svc.health()["swap"]
        final_outcomes = dict(outcomes)
    _observe_recovery(sc["name"], recovery_s)
    return {"phases": {"flywheel": {
        "outcomes_serving": outcomes_serving,
        "outcomes": final_outcomes,
        "submitted": n_clicks + 3,
        "sink": sink_snap,
        "poisoned_records": poisoned,
        "cycle": cycle,
        "flywheel": fw.report(),
        "quarantine": fw.quarantine,
        "swap_state": swap_state,
    }}, "recovery_s": round(recovery_s, 3),
        "firings": plan.injected_total()}


def _run_supervise(sc: dict, work_dir: str) -> dict:
    """crash_loop / preemption_storm / elastic_membership: a REAL
    supervisor (train/supervise.Supervisor) drives chaos child
    processes.  Every attempt is ``dptpu-chaos --child`` with its own
    spec/report pair and ``resume=auto``; the armed plan rides in each
    spec, so per-process visit schedules decide which attempts get
    struck (an attempt whose remaining steps stay below the fault's
    visit index completes cleanly — the storm ends by construction, not
    by disarming).

    Elastic knobs (``params``): ``device_schedule`` gives attempt k its
    own forced device count (the membership-change simulation — attempt
    k+1 seeing a different count IS the preempted/re-added slice) and
    arms the supervisor's topology probe, so exits classify
    ``topology_changed``; ``attempt_overrides`` merges per-attempt
    config overrides (e.g. an explicit grow-into strategy) into that
    attempt's spec."""
    from ..backend_health import pin_cpu8_topology
    from ..train import elastic as elastic_lib
    from ..train.supervise import CrashLoopError, Supervisor
    from .policies import Retry

    params = dict(sc.get("params") or {})
    overrides = _maybe_big_dataset(params, dict(sc.get("overrides") or {}),
                                   work_dir)
    overrides["resume"] = "auto"  # harmless on attempt 0 (no prior run)
    schedule = [int(n) for n in (params.get("device_schedule") or [])]
    attempt_overrides = {int(k): v for k, v in
                         (params.get("attempt_overrides") or {}).items()}

    def make_argv(attempt: int) -> list[str]:
        ov = dict(overrides)
        ov.update(attempt_overrides.get(attempt) or {})
        spec = {"phase": f"attempt{attempt}", "plan": sc.get("plan"),
                "overrides": ov, "work_dir": work_dir,
                "report": os.path.join(work_dir,
                                       f"report_attempt{attempt}.json")}
        path = os.path.join(work_dir, f"spec_attempt{attempt}.json")
        with open(path, "w") as f:
            json.dump(spec, f)
        return [sys.executable, "-m", "distributedpytorch_tpu.chaos",
                "--child", path]

    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env = pin_cpu8_topology(dict(os.environ))
    env.pop(sites.PLAN_ENV, None)  # the plan rides in the specs
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

    def attempt_env(attempt: int) -> dict | None:
        if not schedule:
            return None
        n = schedule[min(attempt, len(schedule) - 1)]
        # the flag grammar lives beside the probe's parser (one owner:
        # train/elastic.py), so the knob we write is the knob it reads
        return {"XLA_FLAGS": elastic_lib.force_device_count_flags(
            env.get("XLA_FLAGS", ""), n)}

    sup = Supervisor(
        make_argv, work_dir=work_dir,
        max_restarts=int(params.get("max_restarts", 8)),
        crash_loop_threshold=int(params.get("crash_loop_threshold", 3)),
        # test-scale naps: the schedule shape is Retry's, the constants
        # are not what the scenario asserts
        backoff=Retry(base_s=0.05, cap_s=0.2),
        env=env, child_env=attempt_env if schedule else None,
        # the topology probe reads the pinned-CPU env directly (no
        # subprocess) — the same fast path a real elastic deployment
        # skips, because its device set is the runtime's to report
        topology_probe=(elastic_lib.probe_topology if schedule
                        else None),
        capture_output=True)
    try:
        sreport = sup.run()
    except CrashLoopError as e:
        sreport = e.report  # a failed invariant, not a runner crash
    attempts = []
    for k in range(sreport["attempts"]):
        rp = os.path.join(work_dir, f"report_attempt{k}.json")
        at: dict = {"attempt": k, "completed_report": False}
        try:
            with open(rp + ".pre") as f:
                at.update(json.load(f))
        except (OSError, ValueError):
            pass
        try:
            with open(rp) as f:
                full = json.load(f)
            at.update(full)
            at["completed_report"] = True
            _book_child_firings(full)
        except (OSError, ValueError):
            pass  # SIGKILLed attempt: preflight evidence only
        attempts.append(at)
    # recovery = supervisor downtime per restart (child death -> next
    # child spawned), each observed into the histogram
    downtimes = sreport.get("recovery_seconds") or []
    for s in downtimes:
        _observe_recovery(sc["name"], s)
    recovery_s = max(downtimes) if downtimes else 0.0
    return {"phases": {"supervise": {
        "supervisor": sreport,
        "attempts": attempts,
        # the supervisor's own classification ledger — what the
        # elastic invariants read ("every exit topology_changed,
        # never crash_loop" must hold in the DURABLE record, not just
        # the in-memory report)
        "events": _read_jsonl(work_dir, "supervisor.jsonl"),
        "device_schedule": schedule,
    }}, "recovery_s": round(recovery_s, 3)}


def _run_fleet(sc: dict, work_dir: str) -> dict:
    """replica_kill_under_load: a real local fleet (serve/fleet.py) of
    ``--fresh-init`` dptpu-serve children under a session click burst,
    with the armed plan riding in ONE replica's env so that replica
    SIGKILLs itself mid-burst.  The runner process stays clean — it
    plays the operator: spawn, load, watch the failover/rehash/respawn
    machinery do its job, and read the verdict off the client outcomes
    and the fleet's flight-recorder events."""
    import threading

    import numpy as np

    from ..backend_health import pin_cpu8_topology
    from ..serve.client import ServeClient
    from ..serve.fleet import FleetFront, LocalManager
    from ..serve.router import HashRing
    from ..serve.service import (
        DeadlineExceededError,
        QueueFullError,
        ServiceUnhealthyError,
    )
    from ..telemetry import events as events_lib

    params = dict(sc.get("params") or {})
    size = int(params.get("size", 48))
    n_replicas = int(params.get("replicas", 3))
    per_replica = int(params.get("sessions_per_replica", 2))
    warm_clicks = int(params.get("warm_clicks", 4))

    # the fleet's events ARE the scenario's clock: replica_down ->
    # replica_up spans (one process's ts_mono) measure recovery
    log = events_lib.configure(work_dir)
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    pinned = pin_cpu8_topology({})
    pinned["PYTHONPATH"] = repo + os.pathsep + os.environ.get(
        "PYTHONPATH", "")
    plan_json = json.dumps(dict(sc.get("plan") or {}, name=sc["name"]))

    def child_env(rid: str, restarts: int) -> dict:
        extra = dict(pinned)
        # the plan rides in ONE slot's FIRST boot only: r0 self-SIGKILLs
        # on its scheduled serve/drain visit, its respawn (restarts > 0)
        # and every other replica serve clean.  The empty value also
        # masks any plan the operator exported to the runner's own env
        # (maybe_arm_from_env treats "" as unset).
        extra[sites.PLAN_ENV] = (plan_json if rid == "r0" and restarts == 0
                                 else "")
        return extra

    template = [sys.executable, "-m", "distributedpytorch_tpu.serve",
                "--fresh-init", str(size), "--warmup",
                "--max-batch", str(int(params.get("max_batch", 4))),
                "--max-wait-ms", "0",
                "--queue-depth", str(int(params.get("queue_depth", 32)))]
    manager = LocalManager(template,
                           workdir=os.path.join(work_dir, "replicas"),
                           max_restarts=3, child_env=child_env)
    front = FleetFront(manager=manager, replicas=n_replicas,
                       poll_interval_s=float(
                           params.get("poll_interval_s", 0.25)),
                       boot_timeout_s=600.0)

    # Session ids chosen so EVERY replica owns sessions: the ring's
    # blake2b hash is process-independent, so the owner of "s<i>" under
    # slots r0..rN-1 is computable right here — the victim is guaranteed
    # resident sessions to rehash, and the at=[4] visit schedule (2 cold
    # clicks, then the burst) is deterministic rather than hash-lucky.
    ring = HashRing([f"r{i}" for i in range(n_replicas)])
    by_owner: dict[str, list[str]] = {f"r{i}": [] for i in range(n_replicas)}
    i = 0
    while any(len(v) < per_replica for v in by_owner.values()):
        sid = f"s{i}"
        i += 1
        owner = ring.lookup(sid)
        if len(by_owner[owner]) < per_replica:
            by_owner[owner].append(sid)
    sessions = [sid for sids in by_owner.values() for sid in sids]

    rng = np.random.RandomState(0)
    image = rng.randint(0, 256, (size, size, 3)).astype(np.uint8)
    q, m = size // 4, size // 2
    base_points = np.array([[q, m], [size - q, m], [m, q], [m, size - q]],
                           np.float64)

    outcomes = {"completed": 0, "typed_shed": 0, "untyped_error": 0}
    served_by: dict[str, list] = {sid: [] for sid in sessions}
    rerouted_from: list[str] = []
    errors: list[str] = []
    lock = threading.Lock()

    def click(client: ServeClient, sid: str, k: int) -> None:
        try:
            mask = client.predict(
                image, np.clip(base_points + (k % 3), 0, size - 1),
                session_id=sid)
            finite = bool(np.isfinite(mask).all())
            with lock:
                outcomes["completed" if finite else "untyped_error"] += 1
                served_by[sid].append(client.last_fleet["replica"])
                if client.last_fleet["rerouted"]:
                    rerouted_from.append(client.last_fleet["rerouted"])
        except (QueueFullError, DeadlineExceededError,
                ServiceUnhealthyError):
            # the WHOLE typed taxonomy (SessionLaneFull and
            # ReplicaDraining subclass these) — sheds, not failures
            with lock:
                outcomes["typed_shed"] += 1
        except Exception as e:  # noqa: BLE001 — that's the point
            with lock:
                outcomes["untyped_error"] += 1
                errors.append(f"{sid}: {type(e).__name__}: {e}")

    submitted = 0
    try:
        front.start()
        url = front.serve_http("127.0.0.1", 0)
        assert front.wait_live(n_replicas, timeout_s=600.0), \
            f"fleet never reached {n_replicas} live replicas"
        # one client PER session: last_fleet is per-client state, and
        # the per-session replica trail is the rehash evidence
        clients = {sid: ServeClient(url, timeout_s=300.0, shed_retries=3,
                                    retry_seed=7)
                   for sid in sessions}
        # phase 1 — establish every session, serially: one cold click
        # each, so the pre-kill owner map is unambiguous (and the
        # victim's serve/drain visit count advances predictably)
        for sid in sessions:
            click(clients[sid], sid, 0)
            submitted += 1
        owners_pre = {sid: (served_by[sid][0] if served_by[sid] else None)
                      for sid in sessions}
        # phase 2 — the warm burst, all sessions concurrent; the
        # victim's visit schedule fires mid-burst and SIGKILLs it
        def run_session(sid: str) -> None:
            for k in range(1, warm_clicks + 1):
                click(clients[sid], sid, k)

        threads = [threading.Thread(target=run_session, args=(sid,),
                                    name=f"click-{sid}")
                   for sid in sessions]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        submitted += warm_clicks * len(sessions)
        # phase 3 — convergence: the supervisor respawns the dead slot
        # (same id -> same ring ranges) and the ring returns to full
        # count; then every session clicks once more — moved sessions
        # complete via one re-encode, homed-again sessions likewise
        deadline = time.monotonic() + 600.0
        while (time.monotonic() < deadline
               and front.registry.n_live() < n_replicas):
            time.sleep(0.1)
        health_final = front.health()
        for sid in sessions:
            click(clients[sid], sid, 99)
            submitted += 1
    finally:
        front.stop()
        events_lib.release(log)

    evs = [e for e in events_lib.read_events_file(log.path or "")
           if e["source"] == "fleet"]
    downs = [e for e in evs if e["kind"] == "replica_down"]
    killed = downs[0]["payload"]["replica"] if downs else None
    recovery_s = None
    if killed is not None:
        t_down = downs[0]["ts_mono"]
        ups = [e for e in evs if e["kind"] == "replica_up"
               and e["payload"].get("replica") == killed
               and e["ts_mono"] > t_down]
        if ups:
            recovery_s = ups[0]["ts_mono"] - t_down
    if recovery_s is not None:
        _observe_recovery(sc["name"], recovery_s)
    # the rehash evidence: sessions the dead replica owned that later
    # completed a click on a DIFFERENT replica (the re-encode path)
    moved = sorted(sid for sid, owner in owners_pre.items()
                   if owner == killed
                   and any(rep not in (None, killed)
                           for rep in served_by[sid][1:]))
    return {"phases": {"fleet": {
        "outcomes": outcomes,
        "submitted": submitted,
        "errors": errors[:8],
        "owners_pre": owners_pre,
        "served_by": served_by,
        "killed": killed,
        "moved_sessions": moved,
        "rerouted_from": sorted(set(rerouted_from)),
        "failovers": sum(1 for e in evs if e["kind"] == "failover"),
        "event_kinds": sorted({e["kind"] for e in evs}),
        "health_final": {
            "live": health_final["live"],
            "ring": health_final["ring"],
            "states": {rid: r["state"] for rid, r in
                       health_final["replicas"].items()},
        },
    }}, "recovery_s": (round(recovery_s, 3)
                       if recovery_s is not None else None)}


# -------------------------------------------------------------- invariants

def _check(sc: dict, result: dict) -> dict:
    """Evaluate the scenario's named invariants against the phase
    reports; returns {name: {ok, detail}}."""
    phases = result["phases"]
    out: dict[str, dict] = {}

    def verdict(name, ok, detail):
        out[name] = {"ok": bool(ok), "detail": detail}

    for name in sc.get("invariants", ()):
        try:
            _check_one(name, sc, result, phases, verdict)
        except Exception as e:
            # a scenario naming an invariant its mode never produced
            # (e.g. preempted_cleanly on a plain fit) is a FAILED
            # verdict with the reason, never a runner crash
            verdict(name, False,
                    f"invariant not evaluable for this scenario "
                    f"({type(e).__name__}: {e})")
    return out


def _check_one(name, sc, result, phases, verdict):
    """One named invariant -> one verdict() call (see :func:`_check`)."""
    if True:  # kept one level deep so the elif-chain below reads as a table
        if name == "preempted_cleanly":
            p1 = phases["fault"]
            verdict(name,
                    p1["preempted"] and p1["latest_step"] == p1["final_step"],
                    f"preempted={p1['preempted']} "
                    f"latest_step={p1['latest_step']} "
                    f"final_step={p1['final_step']}")
        elif name == "stopped_mid_epoch":
            p1 = phases["fault"]
            verdict(name, 0 < p1["final_step"] < p1["nb"],
                    f"stopped at step {p1['final_step']} of a "
                    f"{p1['nb']}-step epoch")
        elif name == "params_restored_exactly":
            p1, p2 = phases["fault"], phases["resume"]
            verdict(name,
                    p2["param_digest_at_restore"] == p1["param_digest"],
                    f"saved {p1['param_digest'][:12]} vs restored "
                    f"{p2['param_digest_at_restore'][:12]}")
        elif name == "zero_lost_or_duplicated_steps":
            p1, p2 = phases["fault"], phases["resume"]
            expected = p2["nb"] * _scenario_epochs(sc)
            trained = p1["final_step"] + (p2["final_step"]
                                          - p2["restored_step"])
            verdict(name,
                    p2["final_step"] == expected and trained == expected,
                    f"expected {expected} steps, final {p2['final_step']}, "
                    f"trained {trained} "
                    f"(phase1 {p1['final_step']} + phase2 "
                    f"{p2['final_step'] - p2['restored_step']})")
        elif name == "resharded_across_plans":
            p1, p2 = phases["fault"], phases["resume"]
            saved = p2.get("restored_meta_plan") or {}
            live = p2.get("plan") or {}
            verdict(name,
                    bool(saved) and bool(live)
                    # the meta named the SAVER's plan (the loud half:
                    # the crossing is detectable, never silent)...
                    and saved == (p1.get("plan") or {})
                    # ...and the resumed process really crossed into a
                    # model-axis-sharded layout
                    and saved != live and bool(live.get("shard_params")),
                    f"checkpoint meta plan {saved} -> restored under "
                    f"{live} (phase-1 plan "
                    f"{(p1.get('plan') or {}).get('strategy')})")
        elif name == "fell_back_past_torn_checkpoint":
            p1, p2 = phases["fault"], phases["resume"]
            torn = max(p1["saved_steps"])
            complete = max(s for s in p1["saved_steps"] if s != torn)
            verdict(name,
                    torn in p2["restore_fallback"]
                    and p2["restored_step"] == complete,
                    f"saved {p1['saved_steps']}, fallback skipped "
                    f"{p2['restore_fallback']}, restored at "
                    f"{p2['restored_step']} (want {complete})")
        elif name == "completed_after_fallback":
            p1, p2 = phases["fault"], phases["resume"]
            expected = max(p1["saved_steps"])  # the full schedule's end
            verdict(name, p2["final_step"] == expected
                    and not p2["preempted"],
                    f"final {p2['final_step']} (want {expected}), "
                    f"preempted={p2['preempted']}")
        elif name == "sheds_instead_of_crashing":
            s = phases["serve"]
            o = s["outcomes"]
            accounted = sum(o.values()) == s["submitted"]
            shed = o["shed_queue_full"] + o["shed_deadline"]
            verdict(name,
                    accounted and shed > 0 and o["other_error"] == 0
                    and s["health_under_fault"]["running"],
                    f"outcomes={o} submitted={s['submitted']} "
                    f"running={s['health_under_fault']['running']}")
        elif name == "recovers_after_disarm":
            s = phases["serve"]
            verdict(name, s["recovered_after_disarm"],
                    f"recovered={s['recovered_after_disarm']} in "
                    f"{result['recovery_s']}s")
        elif name == "zero_session_errors_during_swap":
            s = phases["serve_swap"]
            o = s["outcomes"]
            verdict(name,
                    o["other_error"] == 0 and o["shed"] == 0
                    and o["completed"] == s["submitted"],
                    f"outcomes={o} submitted={s['submitted']} — every "
                    "session click through both swaps must complete")
        elif name == "good_swap_promoted":
            s = phases["serve_swap"]
            st = s["swap_state"]
            verdict(name,
                    st["swaps"]["promoted"] >= 1
                    and st["active"] == s["good_generation"],
                    f"promoted={st['swaps']['promoted']} "
                    f"active={st['active']} "
                    f"(good generation {s['good_generation']})")
        elif name == "sessions_survive_swap":
            s = phases["serve_swap"]
            verdict(name, s["old_sessions_warm_after_promote"],
                    "pre-swap session warm-hit its cached features "
                    f"after promote: {s['old_sessions_warm_after_promote']}")
        elif name == "bad_canary_rolled_back":
            s = phases["serve_swap"]
            st = s["swap_state"]
            bad = s["bad_generation"] or {}
            verdict(name,
                    st["swaps"]["rolled_back"] >= 1
                    and st["canary"] is None
                    and bad.get("nonfinite", 0) >= 1
                    and s["recovered_after_rollback"],
                    f"rolled_back={st['swaps']['rolled_back']} "
                    f"canary={st['canary']} bad={bad} "
                    f"recovered={s['recovered_after_rollback']} in "
                    f"{result['recovery_s']}s")
        elif name == "corrupt_entry_falls_back":
            s = phases["serve_aot"]
            f = s["bitflip"]
            # the bitflipped entry must be REFUSED via the checksum
            # gate (fallback 'error', never 'miss' — a miss would mean
            # the rot was invisible) and that program compiled fresh
            compiled = [e for e in f["warmup"]["programs"]
                        if e["outcome"] == "compile"
                        and e.get("fallback") == "error"]
            verdict(name,
                    bool(compiled) and f["served_ok"],
                    f"bitflip boot: fallbacks={f['fallbacks']} "
                    f"programs={f['warmup']['programs']} "
                    f"served_ok={f['served_ok']} (want >=1 checksum "
                    "refusal -> fresh compile, service up)")
        elif name == "truncated_entry_falls_back":
            s = phases["serve_aot"]
            f = s["truncated"]
            compiled = [e for e in f["warmup"]["programs"]
                        if e["outcome"] == "compile"
                        and e.get("fallback") == "error"]
            flagged = s["victim"] in (s["verify_report"]["bad"]
                                      + s["verify_report"]["missing"])
            verdict(name,
                    bool(compiled) and f["served_ok"] and flagged,
                    f"torn-entry boot: fallbacks={f['fallbacks']} "
                    f"served_ok={f['served_ok']}; --verify flagged "
                    f"{s['verify_report']['bad']} (want the torn "
                    f"{s['victim']!r} refused, flagged, served around)")
        elif name == "topology_mismatch_falls_back":
            s = phases["serve_aot"]
            f = s["mismatch"]
            # EVERY program must miss (the foreign-topology manifest
            # invalidates the whole cache) and the boot still serves —
            # a degraded cold start, not a crash
            verdict(name,
                    f["warmup"]["aot_cache"] == "miss"
                    and f["warmup"]["programs_loaded"] == 0
                    and f["fallbacks"] == ["miss"] and f["served_ok"],
                    f"mismatch boot: aot={f['warmup']['aot_cache']} "
                    f"loaded={f['warmup']['programs_loaded']} "
                    f"fallbacks={f['fallbacks']} "
                    f"served_ok={f['served_ok']}")
        elif name == "serves_after_fallback":
            s = phases["serve_aot"]
            boots = [s["bitflip"], s["truncated"], s["mismatch"]]
            bad = [b["tag"] for b in boots
                   if not (b["served_ok"] and b["bitwise_equal"])]
            verdict(name, not bad,
                    f"boots failing serve-or-parity: {bad} (every "
                    "degraded boot must serve masks bitwise identical "
                    "to the jit forward — no silently-wrong executable)")
        elif name == "packed_read_error_typed":
            f = phases["packed_fit"]
            verdict(name,
                    f["typed_error"] == "PackedRecordError"
                    and f["bad_index"] is not None
                    and f["error_names_index"],
                    f"typed_error={f['typed_error']} "
                    f"bad_index={f['bad_index']} "
                    f"names_index={f['error_names_index']} (want the "
                    "typed PackedRecordError naming the record — never "
                    "a silent wrong sample)")
        elif name == "torn_record_detected":
            f = phases["packed_fit"]
            verdict(name,
                    f["bad_index"] is not None
                    and f["bad_index"] in (f["verify_bad"] or []),
                    f"dptpu-pack --verify flagged {f['verify_bad']} "
                    f"(must include the torn record {f['bad_index']}; "
                    "siblings sharing its image blob are legitimately "
                    "flagged too)")
        elif name == "quarantined_run_completes":
            f = phases["packed_fit"]
            verdict(name,
                    not f.get("preempted", True)
                    and f.get("epochs_recorded") == _scenario_epochs(sc)
                    and 0 < f.get("nb_quarantined", 0) <= f["nb_full"],
                    f"quarantined run: epochs_recorded="
                    f"{f.get('epochs_recorded')} "
                    f"nb={f.get('nb_quarantined')}/{f['nb_full']} "
                    f"preempted={f.get('preempted')}")
        elif name == "nonfinite_steps_logged":
            f = phases["fit"]
            # expected count = what the plan ACTUALLY fired (schedule
            # selectors every/times/p make a static count from the spec
            # wrong for user-authored scenarios)
            poisoned = sum(n for (_s, kind), n in
                           (result.get("firings") or {}).items()
                           if kind == "nan")
            verdict(name,
                    poisoned > 0
                    and f["nonfinite_steps_logged"] == poisoned,
                    f"train/nonfinite_steps={f['nonfinite_steps_logged']} "
                    f"(want {poisoned} — the plan's nan firings)")
        elif name == "fit_completes":
            f = phases["fit"]
            verdict(name,
                    not f["preempted"]
                    and f["epochs_recorded"] == _scenario_epochs(sc),
                    f"epochs_recorded={f['epochs_recorded']} "
                    f"preempted={f['preempted']}")
        elif name == "rollback_fired":
            f = phases["fit"]
            rec = f.get("recovery") or {}
            poisoned = sum(n for (_s, kind), n in
                           (result.get("firings") or {}).items()
                           if kind == "nan")
            rollbacks = rec.get("rollbacks") or 0
            # one rollback per poisoned window; several poisons landing
            # in ONE observation window legitimately share a rollback
            verdict(name,
                    poisoned > 0 and 1 <= rollbacks <= poisoned,
                    f"recovery={rec} (want 1..{poisoned} rollbacks for "
                    f"{poisoned} nan firings)")
        elif name == "rolled_back_to_committed":
            f = phases["fit"]
            q = f.get("quarantine") or []
            targets = [r.get("rollback_to_step") for r in q]
            # a MID-RUN committed checkpoint, not the step-0 bootstrap
            verdict(name, bool(targets) and all(t > 0 for t in targets),
                    f"rollback targets {targets} (want all > step 0)")
        elif name == "quarantine_written":
            f = phases["fit"]
            q = f.get("quarantine") or []
            rec = f.get("recovery") or {}
            complete = q and all(
                r.get("batch_indices")
                and r.get("step_start") is not None
                and r.get("step_end") is not None
                and "losses" in r for r in q)
            verdict(name,
                    bool(complete)
                    and (rec.get("quarantined_steps") or 0) >= 1,
                    f"quarantine.jsonl records={q} "
                    f"quarantined_steps={rec.get('quarantined_steps')}")
        elif name == "governor_armed_echo":
            f = phases["fit"]
            arms = [r for r in f.get("governor") or []
                    if r["action"] in ("arm_echo", "raise_echo")
                    and r["applied"]]
            factors = [(r.get("detail") or {}).get("factor")
                       for r in arms]
            verdict(name,
                    bool(arms) and all(b > a >= 1 and b >= 2
                                       for a, b in factors)
                    and all(r["stall"] is not None
                            and r["stall"] > r["target"] for r in arms),
                    f"applied echo arms {factors} at stalls "
                    f"{[r['stall'] for r in arms]} (want >= 1 applied "
                    "arm with factor >= 2, decided above target)")
        elif name == "stall_recovered_below_target":
            f = phases["fit"]
            feed = f.get("feed") or {}
            frac, target = feed.get("input_wait_fraction"), \
                feed.get("target")
            verdict(name,
                    frac is not None and target is not None
                    and frac <= target,
                    f"final windowed input_wait fraction {frac} vs "
                    f"target {target}")
        elif name == "echo_disarmed_after_clear":
            f = phases["fit"]
            recs = f.get("governor") or []
            arm_ts = [r["ts"] for r in recs
                      if r["action"] == "arm_echo" and r["applied"]]
            disarms = [r for r in recs
                       if r["action"] == "disarm_echo" and r["applied"]]
            feed = f.get("feed") or {}
            verdict(name,
                    bool(arm_ts) and bool(disarms)
                    and disarms[-1]["ts"] >= arm_ts[0]
                    and not feed.get("echo_armed")
                    and feed.get("echo_effective") == 1,
                    f"arms at {arm_ts}, disarms at "
                    f"{[r['ts'] for r in disarms]}, final echo "
                    f"{feed.get('echo_effective')} "
                    f"(armed={feed.get('echo_armed')})")
        elif name == "supervisor_recovered_each_crash":
            s = phases["supervise"]
            sup = s["supervisor"]
            expected = int((sc.get("params") or {}).get(
                "expected_crashes", 1))
            verdict(name,
                    sup["outcome"] == "clean"
                    and sup["restarts"]["crashed"] == expected,
                    f"outcome={sup['outcome']} restarts={sup['restarts']} "
                    f"(want {expected} crash restarts, clean finish)")
        elif name == "restored_digest_matches_committed":
            s = phases["supervise"]
            resumed = [a for a in s["attempts"]
                       if a.get("restored_step", 0) > 0
                       and a.get("param_digest_at_restore")]
            mismatches = [
                a["attempt"] for a in resumed
                if a.get("restored_meta_digest")
                != a["param_digest_at_restore"]]
            verdict(name, bool(resumed) and not mismatches,
                    f"{len(resumed)} resumed attempts, digest mismatches "
                    f"at attempts {mismatches} (checkpoint.digest meta vs "
                    "restored param bytes)")
        elif name == "completed_schedule":
            s = phases["supervise"]
            done = [a for a in s["attempts"] if a.get("completed_report")]
            last = done[-1] if done else {}
            expected = (last.get("nb") or 0) * _scenario_epochs(sc)
            verdict(name,
                    bool(last) and not last.get("preempted")
                    and last.get("final_step") == expected,
                    f"final attempt {last.get('attempt')}: "
                    f"final_step={last.get('final_step')} "
                    f"(want {expected}), preempted={last.get('preempted')}")
        elif name == "topology_changed_each_exit":
            s = phases["supervise"]
            sup = s["supervisor"]
            expected = int((sc.get("params") or {}).get(
                "expected_topology_changes", 1))
            ledger = [e for e in s.get("events") or []
                      if e.get("event") == "topology_changed"]
            bad = [e for e in s.get("events") or []
                   if e.get("event") in ("crash", "gave_up")]
            verdict(name,
                    sup["outcome"] == "clean"
                    and sup["restarts"]["topology_changed"] == expected
                    and sup["restarts"]["crashed"] == 0
                    and sup["restarts"]["preempted"] == 0
                    and len(ledger) == expected and not bad,
                    f"outcome={sup['outcome']} restarts={sup['restarts']} "
                    f"ledger topology_changed={len(ledger)} "
                    f"crash/gave_up events={len(bad)} (want {expected} "
                    "topology_changed, zero crash classifications)")
        elif name == "replanned_each_change":
            s = phases["supervise"]
            sup = s["supervisor"]
            schedule = s.get("device_schedule") or []
            changes = sup.get("topology_changes") or []
            # every resumed generation's RESOLVED plan must name the
            # device count its slot in the schedule gave it — the
            # re-plan really happened against the new topology
            mismatched = []
            for a in s["attempts"]:
                k = a.get("attempt", 0)
                if k == 0 or "n_devices" not in a:
                    continue
                want = schedule[min(k, len(schedule) - 1)] \
                    if schedule else None
                if want is not None and a["n_devices"] != want:
                    mismatched.append((k, a["n_devices"], want))
            verdict(name,
                    bool(changes) and all(c.get("replan") for c in changes)
                    and not mismatched,
                    f"topology_changes={changes} plan-vs-schedule "
                    f"mismatches={mismatched}")
        elif name == "plan_crossings_announced":
            s = phases["supervise"]
            # every resumed attempt whose plan differs from the plan
            # the restored meta names must have ANNOUNCED the crossing
            # (trainer.resume_plan_crossing, in the preflight sidecar —
            # later generations get killed)
            resumed = [a for a in s["attempts"]
                       if a.get("restored_step", 0) > 0
                       and a.get("restored_meta_plan") is not None]
            silent = [a["attempt"] for a in resumed
                      if a.get("plan") != a.get("restored_meta_plan")
                      and not a.get("plan_crossing")]
            verdict(name, bool(resumed) and not silent,
                    f"{len(resumed)} resumed attempts, silent plan "
                    f"crossings at attempts {silent} (every crossing "
                    "must be announced at restore)")
        elif name == "preempted_each_wave":
            s = phases["supervise"]
            sup = s["supervisor"]
            expected = int((sc.get("params") or {}).get(
                "expected_preemptions", 1))
            verdict(name,
                    sup["outcome"] == "clean"
                    and sup["restarts"]["preempted"] == expected,
                    f"outcome={sup['outcome']} restarts={sup['restarts']} "
                    f"(want {expected} preempt restarts, clean finish)")
        elif name == "exact_resume_chain":
            s = phases["supervise"]
            atts = s["attempts"]
            breaks = [
                atts[k]["attempt"] for k in range(1, len(atts))
                if atts[k - 1].get("param_digest")
                and atts[k].get("param_digest_at_restore")
                != atts[k - 1]["param_digest"]]
            verdict(name, len(atts) >= 2 and not breaks,
                    f"{len(atts)} attempts; restored-digest chain breaks "
                    f"at attempts {breaks} (each wave must resume the "
                    "exact params the previous wave saved)")
        elif name == "zero_lost_or_duplicated_steps_storm":
            s = phases["supervise"]
            done = [a for a in s["attempts"] if a.get("completed_report")]
            expected = (done[-1].get("nb") or 0) * _scenario_epochs(sc) \
                if done else -1
            trained = sum(a["final_step"] - a["restored_step"]
                          for a in done)
            final = done[-1]["final_step"] if done else -1
            verdict(name, bool(done) and trained == expected
                    and final == expected,
                    f"trained {trained} steps across {len(done)} waves, "
                    f"final {final} (want {expected} for both)")
        elif name == "poisoned_records_quarantined":
            f = phases["flywheel"]
            poisoned = set(f["poisoned_records"])
            quarantined = set(f["quarantine"])
            fired = sum(n for (_s, kind), n in
                        (result.get("firings") or {}).items()
                        if kind == "nan")
            verdict(name,
                    fired > 0 and len(poisoned) == fired
                    and poisoned <= quarantined,
                    f"nan fired {fired}x, poisoned records "
                    f"{sorted(poisoned)}, flywheel quarantine "
                    f"{sorted(quarantined)} (every poisoned record must "
                    "be named in the durable quarantine)")
        elif name == "canary_never_promoted":
            f = phases["flywheel"]
            st = f["swap_state"]
            cyc = f["cycle"]
            verdict(name,
                    cyc.get("action") == "held"
                    and st["swaps"]["promoted"] == 0
                    and st["swaps"]["rolled_back"] == 0
                    and st["active"] == 0 and st["canary"] is None,
                    f"cycle action={cyc.get('action')} "
                    f"(reason={cyc.get('reason')}) swaps={st['swaps']} "
                    f"active={st['active']} (the held fit must never "
                    "reach the canary at all)")
        elif name == "serves_old_generation_zero_errors":
            f = phases["flywheel"]
            o = f["outcomes"]
            verdict(name,
                    o["failed"] == 0 and o["completed"] == f["submitted"],
                    f"outcomes={o} submitted={f['submitted']} — every "
                    "click before, during, and after the poisoned cycle "
                    "must complete finite on generation 0")
        elif name == "zero_untyped_client_errors":
            f = phases["fleet"]
            o = f["outcomes"]
            accounted = o["completed"] + o["typed_shed"]
            verdict(name,
                    o["untyped_error"] == 0 and accounted == f["submitted"]
                    and o["completed"] > 0,
                    f"outcomes={o} submitted={f['submitted']} "
                    f"errors={f['errors']} — every click through the "
                    "replica death must complete or shed TYPED "
                    "(429/504/503), never surface an untyped 5xx")
        elif name == "rehashed_sessions_reencode":
            f = phases["fleet"]
            owned = sorted(sid for sid, o in f["owners_pre"].items()
                           if o == f["killed"])
            verdict(name,
                    f["killed"] is not None and len(owned) > 0
                    and f["moved_sessions"] == owned,
                    f"killed={f['killed']} owned sessions {owned}, "
                    f"moved {f['moved_sessions']} — every session the "
                    "dead replica owned must complete clicks on its "
                    "rehashed replica (one re-encode, not an error)")
        elif name == "ring_converges_full_count":
            f = phases["fleet"]
            h = f["health_final"]
            n = int((sc.get("params") or {}).get("replicas", 3))
            want_ring = sorted(f"r{i}" for i in range(n))
            verdict(name,
                    h["live"] == n and sorted(h["ring"]) == want_ring,
                    f"final live={h['live']} ring={sorted(h['ring'])} "
                    f"states={h['states']} (want {n} live, ring "
                    f"{want_ring}: the respawned slot must REJOIN under "
                    "its old id so its sessions come home)")
        elif name == "recovery_recorded":
            f = phases["fleet"]
            r = result.get("recovery_s")
            verdict(name,
                    r is not None and r > 0
                    and "replica_down" in f["event_kinds"]
                    and f["failovers"] >= 0,
                    f"recovery_s={r} event_kinds={f['event_kinds']} — "
                    "the kill->rejoin span must be measured off the "
                    "fleet's replica_down/replica_up events and "
                    "observed into chaos_recovery_seconds{scenario}")
        elif name == "final_metrics_finite":
            import math

            f = phases["fit"]
            vals = [m.get("loss"), m.get("jaccard")] if (
                m := (f["val"][-1] if f["val"] else None)) else [None]
            ok = all(v is not None and math.isfinite(v) for v in vals)
            verdict(name, ok, f"final val metrics {vals}")
        else:
            verdict(name, False, f"unknown invariant {name!r}")


def _scenario_epochs(sc: dict) -> int:
    return int((sc.get("overrides") or {}).get(
        "epochs", BASE_TRAIN_OVERRIDES["epochs"]))


# ------------------------------------------------------------------ driver

def run_scenario(scenario: str | dict, work_dir: str | None = None,
                 strict: bool = False) -> dict:
    """Run one scenario (name, path, or dict); returns the report.
    ``strict`` raises :class:`ChaosInvariantError` when any invariant
    fails (the report rides on the exception)."""
    sc = load_scenario(scenario) if isinstance(scenario, str) else scenario
    mode = sc.get("mode", "fit")
    cleanup = work_dir is None
    work_dir = work_dir or tempfile.mkdtemp(prefix=f"chaos_{sc['name']}_")
    os.makedirs(work_dir, exist_ok=True)
    fired_before = _registry_firings()
    t0 = time.perf_counter()
    try:
        if mode == "fit_resume":
            result = _run_fit_resume(sc, work_dir)
        elif mode == "fit":
            result = _run_fit(sc, work_dir)
        elif mode == "serve":
            result = _run_serve(sc, work_dir)
        elif mode == "serve_swap":
            result = _run_serve_swap(sc, work_dir)
        elif mode == "serve_aot":
            result = _run_serve_aot(sc, work_dir)
        elif mode == "supervise":
            result = _run_supervise(sc, work_dir)
        elif mode == "packed_fit":
            result = _run_packed_fit(sc, work_dir)
        elif mode == "flywheel":
            result = _run_flywheel(sc, work_dir)
        elif mode == "fleet":
            result = _run_fleet(sc, work_dir)
        else:
            raise ValueError(
                f"unknown scenario mode {mode!r} "
                "(fit | fit_resume | serve | serve_swap | serve_aot | "
                "supervise | packed_fit | flywheel | fleet)")
    finally:
        if cleanup:
            import shutil

            shutil.rmtree(work_dir, ignore_errors=True)
    report = {
        "scenario": sc["name"],
        "mode": mode,
        "invariants": _check(sc, result),
        "recovery_s": result.get("recovery_s"),
        "wall_s": round(time.perf_counter() - t0, 2),
        # THIS run's firings: the registry's counters are process-
        # lifetime monotonic (and shared with any env-armed plan), so
        # the report carries the delta — what this scenario injected
        "chaos_injected_total": {
            k: v - fired_before.get(k, 0)
            for k, v in _registry_firings().items()
            if v - fired_before.get(k, 0)},
        "phases": result["phases"],
    }
    report["ok"] = all(v["ok"] for v in report["invariants"].values())
    if strict and not report["ok"]:
        raise ChaosInvariantError(report)
    return report


def _registry_firings() -> dict:
    """``chaos_injected_total`` as rendered by THIS process's registry
    (includes folded child firings) — the acceptance surface."""
    from ..telemetry import get_registry

    fam = None
    for f in get_registry().collect():
        if f.name == "chaos_injected_total":
            fam = f
            break
    if fam is None:
        return {}
    return {"{" + ",".join(f"{k}={v}" for k, v in c.labels) + "}":
            int(c.value) for c in fam.children()}
