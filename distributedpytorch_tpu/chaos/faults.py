"""Seeded, deterministic fault plans: WHAT fires, WHERE, and WHEN.

A :class:`FaultPlan` maps injection sites (``sites.py`` weaves them into
the real seams — batch fetch, train-step dispatch, checkpoint
save/restore, serve enqueue/drain, device placement) to fault specs.
Every decision is deterministic: selection is by per-site visit index
(``at`` / ``every`` / ``after`` / ``times``) and any probabilistic
selection (``p``) draws from a ``random.Random`` seeded from
``(plan seed, site, kind)`` — the same plan replays the same firings,
which is what makes a chaos scenario an asserted test instead of a
flaky one.

Fault kinds:

* ``latency``   — sleep ``delay_s`` at the site (slow host, slow device);
* ``error``     — raise :class:`InjectedFaultError` (dependency blew up);
* ``nan``       — poison the site's payload: float arrays (numpy or jax)
  filled with NaN, scalars replaced — the divergence-detection driver;
* ``sigterm``   — deliver SIGTERM to this process (preemption, the real
  signal through the real handler — nothing is simulated);
* ``sigkill``   — SIGKILL this process: a hard crash with no graceful
  stop, no final checkpoint, no exit handler (OOM-killer / scheduler
  kill semantics) — the supervisor scenarios' driver.  The firing can
  only be booked by a SURVIVING observer (the supervisor's restart
  counters); this process's registry dies with it;
* ``truncate``  — cut the tail off a file under the site's ``path``
  context (torn checkpoint write / post-commit corruption);
* ``bitflip``   — XOR one byte of an array payload (``offset`` into the
  buffer, default 0): bit rot / a torn read of checksummed bytes — the
  packed data plane's ``data/packed_read`` seam driver (the record crc
  must catch it, typed, never silent).

Every actual firing increments ``chaos_injected_total{site,kind}`` in
the process-wide telemetry registry and is appended to ``plan.firings``
for in-test assertions.
"""

from __future__ import annotations

import json
import os
import random
import signal
import sys
import threading
import time

KINDS = ("latency", "error", "nan", "sigterm", "sigkill", "truncate",
         "bitflip")


class InjectedFaultError(RuntimeError):
    """The exception an ``error``-kind fault raises at its site."""


def _poison_leaf(x):
    """NaN-fill one payload leaf; non-float leaves pass through."""
    import numpy as np

    if isinstance(x, (float,)):
        return float("nan")
    arr = None
    if isinstance(x, np.ndarray):
        arr = x
    else:
        # jax.Array (or anything array-like) — materialize on host; the
        # cost is armed-path only and the poisoned value re-places lazily
        try:
            import jax

            if isinstance(x, jax.Array):
                arr = np.asarray(x)
        except Exception:
            arr = None
    if arr is None or not np.issubdtype(arr.dtype, np.floating):
        return x
    return np.full_like(arr, np.nan)


def poison_payload(payload):
    """NaN-poison every float leaf of ``payload`` (dict/list/tuple trees,
    arrays, scalars); structure and non-float leaves are preserved."""
    if isinstance(payload, dict):
        return {k: poison_payload(v) for k, v in payload.items()}
    if isinstance(payload, tuple) and hasattr(payload, "_fields"):
        # NamedTuple: the constructor wants positional fields, not one
        # iterable like the plain-tuple branch below passes
        return type(payload)(*(poison_payload(v) for v in payload))
    if isinstance(payload, (list, tuple)):
        return type(payload)(poison_payload(v) for v in payload)
    return _poison_leaf(payload)


def flip_payload_byte(payload, offset: int = 0):
    """XOR one byte of an array payload (the deterministic bit-rot /
    torn-read model); non-array or empty payloads pass through.  Always
    flips a PRIVATE copy — the caller's buffer (e.g. an mmap view) is
    never mutated."""
    import numpy as np

    if not isinstance(payload, np.ndarray) or payload.size == 0:
        return payload
    out = np.array(payload)  # private contiguous copy
    flat = out.reshape(-1).view(np.uint8)
    flat[int(offset) % flat.size] ^= 0xFF
    return out


def truncate_file(path: str, fraction: float = 0.5) -> str:
    """Tear the LARGEST file under ``path`` (a file or a directory tree)
    down to ``fraction`` of its bytes — the deterministic stand-in for a
    torn write / post-commit corruption.  Returns the torn file's path.

    Largest-first with lexicographic tie-break keeps the choice stable
    run-to-run; the largest file is the array payload, which is exactly
    what a crashed writer tears in practice.
    """
    if os.path.isfile(path):
        victim = path
    else:
        candidates: list[tuple[int, str]] = []
        for dirpath, _dirnames, filenames in os.walk(path):
            for fname in filenames:
                p = os.path.join(dirpath, fname)
                try:
                    size = os.path.getsize(p)
                except OSError:
                    continue
                if size > 0:
                    candidates.append((size, p))
        if not candidates:
            raise InjectedFaultError(
                f"truncate fault found no non-empty file under {path!r}")
        candidates.sort(key=lambda sp: (-sp[0], sp[1]))
        victim = candidates[0][1]
    size = os.path.getsize(victim)
    with open(victim, "r+b") as f:
        f.truncate(max(0, int(size * fraction)))
    return victim


class FaultSpec:
    """One fault at one site, with a deterministic firing schedule.

    ``at``: explicit 1-based visit indices; ``every``: every Nth visit;
    ``after``: visits to skip first; ``times``: max firings; ``p``:
    seeded per-visit probability.  Unset selectors default to "every
    visit" — combine them to carve out the schedule you mean.
    """

    def __init__(self, site: str, kind: str, *, at=None, every=None,
                 after: int = 0, times=None, p=None, delay_s: float = 0.05,
                 message: str = "", fraction: float = 0.5,
                 offset: int = 0):
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r} "
                             f"({' | '.join(KINDS)})")
        if p is not None and not 0.0 <= p <= 1.0:
            raise ValueError(f"p must be in [0, 1], got {p}")
        if every is not None and int(every) < 1:
            # parse-time, not fire-time: every=0 would otherwise surface
            # as a ZeroDivisionError inside the instrumented hot path —
            # a framework crash indistinguishable from a real bug
            raise ValueError(f"every must be >= 1, got {every}")
        if after < 0 or (times is not None and int(times) < 0):
            raise ValueError(
                f"after/times must be >= 0, got after={after} times={times}")
        self.site = site
        self.kind = kind
        self.at = None if at is None else tuple(int(i) for i in at)
        self.every = None if every is None else int(every)
        self.after = int(after)
        self.times = None if times is None else int(times)
        self.p = p
        self.delay_s = float(delay_s)
        self.message = message
        self.fraction = float(fraction)
        self.offset = int(offset)
        self._fired = 0
        self._rng = None  # seeded by the owning plan

    def to_dict(self) -> dict:
        out = {"site": self.site, "kind": self.kind}
        if self.at is not None:
            out["at"] = list(self.at)
        if self.every is not None:
            out["every"] = self.every
        if self.after:
            out["after"] = self.after
        if self.times is not None:
            out["times"] = self.times
        if self.p is not None:
            out["p"] = self.p
        if self.kind == "latency":
            out["delay_s"] = self.delay_s
        if self.message:
            out["message"] = self.message
        if self.kind == "truncate":
            out["fraction"] = self.fraction
        if self.kind == "bitflip" and self.offset:
            out["offset"] = self.offset
        return out

    def should_fire(self, visit: int) -> bool:
        """Deterministic selection for the ``visit``-th site visit
        (1-based).  NOTE: called once per visit in order — the seeded
        ``p`` draw advances per visit, which is what keeps a
        probabilistic schedule replayable."""
        if self.times is not None and self._fired >= self.times:
            return False
        if visit <= self.after:
            return False
        if self.at is not None and visit not in self.at:
            return False
        if self.every is not None and (visit - self.after) % self.every:
            return False
        if self.p is not None and self._rng.random() >= self.p:
            return False
        return True


class FaultPlan:
    """A named, seeded set of :class:`FaultSpec` keyed by site.

    ``fire(site, payload, **ctx)`` is called by ``sites.fire`` on every
    visit to an armed site: it advances that site's visit counter, fires
    any due specs (latency sleeps, error raises, sigterm kills, truncate
    tears ``ctx['path']``, nan returns a poisoned payload), books each
    firing as ``chaos_injected_total{site,kind}``, and returns the
    (possibly poisoned) payload.
    """

    def __init__(self, faults, *, seed: int = 0, name: str = "adhoc"):
        self.name = name
        self.seed = int(seed)
        self.faults: list[FaultSpec] = list(faults)
        self._by_site: dict[str, list[FaultSpec]] = {}
        for f in self.faults:
            # per-spec RNG seeded from (plan seed, site, kind, index):
            # independent streams, reproducible regardless of which other
            # sites fire in between
            f._fired = 0
            f._rng = random.Random(
                f"{self.seed}/{f.site}/{f.kind}/{len(self._by_site.get(f.site, []))}")
            self._by_site.setdefault(f.site, []).append(f)
        self._visits: dict[str, int] = {}
        #: (site, kind, visit) tuples of every firing, in order
        self.firings: list[tuple[str, str, int]] = []
        #: serializes visit counting + schedule decisions: serve/enqueue
        #: fires from N client threads and device/put from the prefetch
        #: worker, and the determinism contract (same plan -> same
        #: firings) dies the moment two threads race a visit index
        self._lock = threading.Lock()

    # ------------------------------------------------------------ serde
    @classmethod
    def from_dict(cls, obj: dict) -> "FaultPlan":
        faults = [FaultSpec(**spec) for spec in obj.get("faults", ())]
        return cls(faults, seed=obj.get("seed", 0),
                   name=obj.get("name", "adhoc"))

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        return cls.from_dict(json.loads(text))

    def to_dict(self) -> dict:
        return {"name": self.name, "seed": self.seed,
                "faults": [f.to_dict() for f in self.faults]}

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    # ------------------------------------------------------------ firing
    def sites(self) -> list[str]:
        return sorted(self._by_site)

    def injected_total(self) -> dict:
        """``{(site, kind): count}`` of the firings so far."""
        out: dict[tuple[str, str], int] = {}
        for site, kind, _visit in self.firings:
            out[site, kind] = out.get((site, kind), 0) + 1
        return out

    def fire(self, site: str, payload=None, **ctx):
        specs = self._by_site.get(site)
        if not specs:
            return payload
        # decide under the lock (visit index, schedule, RNG draws, the
        # `times` budget); ACT outside it — an injected sleep must stall
        # only its own thread, exactly like the real slowness it models.
        # The firing RECORD (plan.firings + the registry counter) is
        # written per spec at the moment it acts, so an error-kind fault
        # aborting the visit leaves no phantom record for the specs it
        # pre-empted (their consumed `times` budget is the one trace of
        # the aborted visit).
        with self._lock:
            visit = self._visits.get(site, 0) + 1
            self._visits[site] = visit
            due = []
            for spec in specs:
                if spec.should_fire(visit):
                    spec._fired += 1
                    due.append(spec)
        for spec in due:
            with self._lock:
                self.firings.append((site, spec.kind, visit))
            self._book(site, spec.kind)
            if spec.kind == "latency":
                time.sleep(spec.delay_s)
            elif spec.kind == "error":
                raise InjectedFaultError(
                    spec.message or f"injected fault at {site} "
                    f"(visit {visit}, plan {self.name!r})")
            elif spec.kind == "sigterm":
                os.kill(os.getpid(), signal.SIGTERM)
            elif spec.kind == "sigkill":
                # flush whatever the process has written — the POINT is
                # that nothing else (handlers, atexit, orbax waits) runs
                try:
                    sys.stdout.flush()
                    sys.stderr.flush()
                except Exception:
                    pass
                os.kill(os.getpid(), signal.SIGKILL)
            elif spec.kind == "truncate":
                path = ctx.get("path")
                if not path:
                    raise InjectedFaultError(
                        f"truncate fault at {site} needs a path= context "
                        "(site not wired for truncation?)")
                truncate_file(path, spec.fraction)
            elif spec.kind == "nan":
                payload = poison_payload(payload)
            elif spec.kind == "bitflip":
                payload = flip_payload_byte(payload, spec.offset)
        return payload

    @staticmethod
    def _book(site: str, kind: str) -> None:
        # armed-path only; deferred so the chaos package imports without
        # pulling the telemetry stack (backend_health imports policies
        # before jax is configured)
        from ..telemetry import events as events_lib
        from ..telemetry import get_registry

        get_registry().counter(
            "chaos_injected_total",
            "Deterministic fault-injection firings (chaos/)",
            labels={"site": site, "kind": kind}).inc()
        # flight recorder: the fault firing is every chaos episode's
        # ground-truth opening anchor
        events_lib.emit("chaos", kind, payload={"site": site})
