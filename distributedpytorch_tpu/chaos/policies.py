"""The framework's ONE retry/backoff, timeout, and circuit-breaker.

Before this module, three call-sites hand-rolled the same failure
policies with drifting semantics: ``backend_health`` polled its probe
with an inline exponential-backoff loop, ``backend_health.device_op_alive``
hand-built a daemon-thread timeout, and ``train/logging.CometWriter``
kept its own consecutive-failure counter.  Each was correct alone;
together they were three slightly different answers to "how do we
survive a flaky dependency".  These classes are the one answer, and the
chaos runner (``chaos/runner.py``) is what exercises them under injected
faults.

Deliberately stdlib-only (no jax, no numpy): ``backend_health`` imports
this BEFORE jax so the probe's fallback can still set ``JAX_PLATFORMS``.
``time.sleep``/clock calls resolve through the ``time`` module at call
time, so tests that patch ``time.sleep``/``time.time`` (the existing
bench-record suite does) drive these policies too.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Callable


class RetryBudgetExceededError(RuntimeError):
    """Every attempt failed and the retry budget (attempts/deadline) is
    spent; ``__cause__`` carries the last exception."""


class PolicyTimeoutError(TimeoutError):
    """The wrapped call exceeded its :class:`Timeout` bound."""


class CircuitOpenError(RuntimeError):
    """The breaker is open: calls are refused without touching the
    protected dependency."""


class Retry:
    """Exponential backoff with optional jitter, bounded by attempt count
    and/or wall-clock deadline.

    The backoff sequence is ``min(cap_s, base_s * 2**(attempt-1))`` (the
    exponent clamped so an unbounded poll can't overflow float math — the
    rule ``backend_health`` always used), optionally jittered by a seeded
    ``random.Random`` so N clients retrying the same outage don't
    stampede in lockstep while tests stay deterministic.

    Two success models:

    * exception-driven (default): ``fn`` raising one of ``retry_on``
      means "retry"; anything else propagates; a return is success.
    * poll-driven (``until``): ``fn``'s RESULT is judged by the
      predicate; a falsy verdict retries.  When the budget runs out the
      LAST result is returned (the caller inspects it) — the shape of a
      health poll, where "still unhealthy at deadline" is an answer,
      not an error.

    ``min_sleep_s`` floors each nap under a deadline (a nearly-expired
    window should still nap briefly, not busy-spin), while the deadline
    itself caps the nap so the final sleep never overshoots the window.
    ``sleep``/``clock`` default to the ``time`` module's, looked up at
    call time — injectable for tests, patchable via ``time``.
    """

    #: exponent clamp: 2**30 seconds is already ~34 years of backoff
    MAX_EXPONENT = 30

    def __init__(self, base_s: float = 0.5, cap_s: float = 30.0, *,
                 attempts: int | None = None,
                 deadline_s: float | None = None,
                 jitter: float = 0.0, min_sleep_s: float = 0.0,
                 seed: int | None = None,
                 sleep: Callable[[float], None] | None = None,
                 clock: Callable[[], float] | None = None):
        if base_s < 0 or cap_s < 0:
            raise ValueError(f"backoff must be >= 0, got base={base_s} "
                             f"cap={cap_s}")
        if not 0.0 <= jitter < 1.0:
            raise ValueError(f"jitter is a fraction in [0, 1), got {jitter}")
        if attempts is not None and attempts < 1:
            raise ValueError(f"attempts must be >= 1, got {attempts}")
        self.base_s = base_s
        self.cap_s = cap_s
        self.attempts = attempts
        self.deadline_s = deadline_s
        self.jitter = jitter
        self.min_sleep_s = min_sleep_s
        self._rng = random.Random(seed)
        self._sleep = sleep
        self._clock = clock

    def backoff_s(self, attempt: int) -> float:
        """Nap after the ``attempt``-th failure (1-based), pre-clamping."""
        b = min(self.cap_s,
                self.base_s * (2 ** min(attempt - 1, self.MAX_EXPONENT)))
        if self.jitter:
            b *= 1.0 + self._rng.uniform(-self.jitter, self.jitter)
        return b

    def sleep(self, seconds: float) -> None:
        """Nap through this policy's (injectable) sleep — the public
        surface for callers that drive their own retry loop but want the
        policy's backoff curve and test injection (the supervisor)."""
        if seconds > 0:
            (self._sleep or time.sleep)(seconds)

    def call(self, fn: Callable[[], Any], *,
             retry_on: tuple = (Exception,),
             until: Callable[[Any], bool] | None = None,
             on_attempt: Callable[[int, Any, float], None] | None = None
             ) -> Any:
        """Run ``fn`` under the policy; see the class docstring for the
        two success models.  ``on_attempt(attempt, outcome, remaining_s)``
        fires after each FAILED attempt (outcome is the result or the
        exception; remaining_s is ``inf`` without a deadline)."""
        clock = self._clock or time.monotonic
        sleep = self._sleep or time.sleep
        deadline = None if self.deadline_s is None \
            else clock() + self.deadline_s
        attempt = 0
        while True:
            attempt += 1
            err: BaseException | None = None
            result = None
            try:
                result = fn()
                if until is None or until(result):
                    return result
            except retry_on as e:
                err = e
            remaining = float("inf") if deadline is None \
                else deadline - clock()
            if on_attempt is not None:
                on_attempt(attempt, err if err is not None else result,
                           remaining)
            out_of_budget = (
                (self.attempts is not None and attempt >= self.attempts)
                or (deadline is not None and remaining <= 0))
            if out_of_budget:
                if err is None and until is not None:
                    return result  # poll mode: the last answer IS the answer
                raise RetryBudgetExceededError(
                    f"{attempt} attempts exhausted") from err
            nap = self.backoff_s(attempt)
            if deadline is not None:
                nap = min(nap, max(self.min_sleep_s, remaining))
            if nap > 0:
                sleep(nap)


class Timeout:
    """Hard wall-clock bound on a call that may never return.

    The call runs on a daemon thread joined with a timeout: on expiry the
    caller gets :class:`PolicyTimeoutError` and the stuck thread is
    abandoned — acceptable for probes in a process whose orchestrator
    will restart it anyway (the contract ``device_op_alive`` always had).
    This is NOT cancellation: the wedged work keeps its thread.  Use for
    liveness probes, never around state mutations.

    Abandoned workers are RECORDED, not forgotten: each leak bumps the
    ``chaos_timeout_threads_leaked`` counter, and :meth:`reap` (run at
    the top of every call) joins any that have since finished — a
    recovering dependency frees its threads instead of accumulating one
    zombie per timeout for the process lifetime.
    """

    def __init__(self, timeout_s: float):
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
        self.timeout_s = timeout_s
        #: workers abandoned past their deadline, reaped opportunistically
        self._leaked: list[threading.Thread] = []

    def reap(self) -> int:
        """Join leaked workers that have since finished; returns how many
        are STILL wedged.  Runs at the top of every :meth:`call` so a
        policy whose probe recovers late frees its thread on the next
        use, not at process exit — the jaxrace JR-flagged blocking call,
        made observable and bounded."""
        still = []
        for t in self._leaked:
            t.join(0)
            if t.is_alive():
                still.append(t)
        self._leaked = still
        return len(still)

    @property
    def leaked_threads(self) -> int:
        """Currently-abandoned (still running) workers."""
        return len(self._leaked)

    @staticmethod
    def _count_leak() -> None:
        # lazy: this module stays stdlib-only and importable pre-jax
        # (backend_health imports it before choosing a platform)
        try:
            from ..telemetry.registry import get_registry, is_enabled

            if is_enabled():
                get_registry().counter(
                    "chaos_timeout_threads_leaked",
                    "Timeout workers abandoned past their deadline"
                ).inc()
        except Exception:  # noqa: BLE001 — accounting must never raise
            pass

    def call(self, fn: Callable[[], Any]) -> Any:
        self.reap()
        box: dict = {}

        def run() -> None:
            try:
                box["value"] = fn()
            except BaseException as e:  # noqa: BLE001 — re-raised below
                box["error"] = e

        t = threading.Thread(target=run, daemon=True)
        t.start()
        t.join(self.timeout_s)
        if t.is_alive():
            self._leaked.append(t)
            self._count_leak()
            raise PolicyTimeoutError(
                f"call exceeded {self.timeout_s}s (worker abandoned; "
                f"{len(self._leaked)} leaked, reaped on next call)")
        if "error" in box:
            raise box["error"]
        return box["value"]


class CircuitBreaker:
    """Consecutive-failure breaker: after ``failure_threshold`` failures
    in a row the circuit opens and calls are refused
    (:class:`CircuitOpenError`) instead of hammering a dead dependency.
    Any success closes it and zeroes the count (non-consecutive failures
    never open it — the CometWriter contract its tests pin).

    ``reset_after_s`` re-arms an open breaker for ONE probe call after a
    cooldown (half-open); omit it for a permanently-latching breaker
    (the right shape when the owner replaces the dependency on open, as
    the Comet writer does by dropping its experiment handle).
    """

    def __init__(self, failure_threshold: int = 5, *,
                 reset_after_s: float | None = None,
                 clock: Callable[[], float] | None = None):
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}")
        self.failure_threshold = failure_threshold
        self.reset_after_s = reset_after_s
        self._clock = clock
        self._lock = threading.Lock()
        self._failures = 0  # jaxrace: guarded-by=self._lock
        self._opened_at: float | None = None  # jaxrace: guarded-by=self._lock

    @property
    def failures(self) -> int:
        """Consecutive failures so far (0 after any success)."""
        with self._lock:
            return self._failures

    @property
    def is_open(self) -> bool:
        with self._lock:
            return self._opened_at is not None

    def _half_open_ready(self) -> bool:
        if self._opened_at is None or self.reset_after_s is None:
            return False
        clock = self._clock or time.monotonic
        return clock() - self._opened_at >= self.reset_after_s

    def call(self, fn: Callable[[], Any]) -> Any:
        with self._lock:
            if self._opened_at is not None:
                if not self._half_open_ready():
                    raise CircuitOpenError(
                        f"circuit open after {self._failures} consecutive "
                        "failures")
                # claim the ONE half-open probe slot: restarting the
                # cooldown under the lock makes concurrent callers see
                # not-ready and stay refused until this probe resolves
                # (success closes; failure leaves the fresh cooldown)
                clock = self._clock or time.monotonic
                self._opened_at = clock()
        try:
            result = fn()
        except BaseException:
            with self._lock:
                self._failures += 1
                if self._failures >= self.failure_threshold:
                    clock = self._clock or time.monotonic
                    self._opened_at = clock()
            raise
        with self._lock:
            self._failures = 0
            self._opened_at = None
        return result
