"""chaos — deterministic fault injection + unified failure policies.

The framework already HANDLES failures (PreemptionGuard's graceful stop,
serve load-shedding, the trainer's non-finite-loss detection, checkpoint
restore fallback); this package is what PROVOKES them on demand, so the
recovery paths are exercised by asserted scenarios instead of waiting
for production to test them:

* :mod:`sites`    — named injection sites woven into the real seams,
  armed process-wide (one attribute check when disabled);
* :mod:`faults`   — seeded, deterministic fault plans (latency, raised
  errors, NaN payload poisoning, SIGTERM delivery, checkpoint
  truncation), every firing booked as
  ``chaos_injected_total{site,kind}``;
* :mod:`policies` — the one Retry/backoff-with-jitter, Timeout and
  CircuitBreaker (stdlib-only; adopted by ``backend_health``, the serve
  client and the Comet writer);
* :mod:`runner`   — JSON scenarios that run a short fit or serve burst
  under a named plan and ASSERT the recovery invariants
  (``dptpu-chaos`` / ``python -m distributedpytorch_tpu.chaos``).

Import-light on purpose: importing this package touches neither jax nor
the telemetry stack (``backend_health`` pulls :mod:`policies` before the
platform is pinned).
"""

from . import faults, policies, sites
from .faults import FaultPlan, FaultSpec, InjectedFaultError
from .policies import (
    CircuitBreaker,
    CircuitOpenError,
    PolicyTimeoutError,
    Retry,
    RetryBudgetExceededError,
    Timeout,
)
from .sites import (
    active_scenario,
    arm,
    armed,
    armed_plan,
    disarm,
    fire,
    inject,
    maybe_arm_from_env,
)

__all__ = [
    "CircuitBreaker", "CircuitOpenError", "FaultPlan", "FaultSpec",
    "InjectedFaultError", "PolicyTimeoutError", "Retry",
    "RetryBudgetExceededError", "Timeout", "active_scenario", "arm",
    "armed", "armed_plan", "disarm", "faults", "fire", "inject",
    "maybe_arm_from_env", "policies", "runner", "sites",
]


def __getattr__(name):  # lazy: runner pulls the train stack
    if name == "runner":
        import importlib

        # importlib, not `from . import`: the from-import consults this
        # very __getattr__ before importing and would recurse
        return importlib.import_module(".runner", __name__)
    raise AttributeError(name)
