"""``dptpu-chaos`` / ``python -m distributedpytorch_tpu.chaos``.

Run a chaos scenario (builtin name or a JSON file) and assert its
invariants::

    dptpu-chaos preempt_mid_epoch            # SIGTERM -> resume, exact
    dptpu-chaos truncated_checkpoint         # torn file -> fallback
    dptpu-chaos serve_latency_shed           # saturation -> 429/504
    dptpu-chaos nan_loss                     # poison -> rollback+replay
    dptpu-chaos nan_loss_legacy              # sentinel off: log+continue
    dptpu-chaos divergence_rollback          # mid-run poison -> rollback
                                             # to a COMMITTED checkpoint
    dptpu-chaos crash_loop                   # SIGKILL x3 -> supervisor
    dptpu-chaos preemption_storm             # SIGTERM storm -> exact chain
    dptpu-chaos elastic_membership           # pod reshaped x3 -> re-plan
                                             # + restore through the plan
                                             # crossing, zero lost steps
    dptpu-chaos input_stall_recovery         # slow feed -> governor arms
                                             # echo -> recovers -> disarms
    dptpu-chaos torn_pack                    # bit-rotted packed record ->
                                             # typed checksum error ->
                                             # --verify + quarantine-by-
                                             # index run completes
    dptpu-chaos poisoned_flywheel            # NaN-poisoned session log ->
                                             # sentinel quarantines exact
                                             # records, canary never
                                             # promotes, fleet serves on
    dptpu-chaos my_scenario.json
    dptpu-chaos --list
    dptpu-chaos --plan preempt_mid_epoch     # print the plan JSON (for
                                             # DPTPU_CHAOS_PLAN arming)

Exit 0 when every invariant holds, 1 otherwise; the full report prints
as the FINAL JSON object on stdout either way (an in-process fit's own
warnings — e.g. the non-finite-loss sweep — may precede it).  Like the jaxaudit CLI, a standalone run
pins the canonical 8-device CPU topology (tests/conftest.py's) before
jax initializes so scenarios are deterministic anywhere; export
``JAX_PLATFORMS`` to target real hardware instead.
"""

from __future__ import annotations

import json
import sys


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="dptpu-chaos",
        description="deterministic fault-injection scenarios "
                    "(see docs/DESIGN.md 'Fault injection & failure "
                    "policies')")
    parser.add_argument("scenario", nargs="?",
                        help="builtin scenario name or a JSON file")
    parser.add_argument("--list", action="store_true",
                        help="list builtin scenarios")
    parser.add_argument("--plan", action="store_true",
                        help="print the scenario's fault plan JSON "
                             "(usable as DPTPU_CHAOS_PLAN) and exit")
    parser.add_argument("--work-dir", default=None,
                        help="keep scenario artifacts here instead of a "
                             "throwaway temp dir")
    parser.add_argument("--child", metavar="SPEC",
                        help=argparse.SUPPRESS)  # internal phase runner
    args = parser.parse_args(argv)

    from ..backend_health import pin_cpu8_topology

    pin_cpu8_topology()
    from . import runner

    if args.child:
        return runner.child_fit(args.child)
    if args.list:
        for name, sc in runner.SCENARIOS.items():
            first = (sc.get("invariants") or [""])[0]
            print(f"{name:22s} mode={sc['mode']:10s} asserts {first}, ...")
        return 0
    if not args.scenario:
        parser.error("a scenario name/file is required (or --list)")
    sc = runner.load_scenario(args.scenario)
    if args.plan:
        plan = dict(sc.get("plan") or {})
        plan.setdefault("name", sc["name"])
        print(json.dumps(plan))
        return 0
    report = runner.run_scenario(sc, work_dir=args.work_dir)
    print(json.dumps(report, indent=2))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
