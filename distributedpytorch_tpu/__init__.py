"""distributedpytorch_tpu — a TPU-native training framework.

A brand-new JAX/XLA framework with the capabilities of the reference repo
``ahmedhshahin/distributedPyTorch`` (an interactive-segmentation training
harness: instance-level Pascal VOC, extreme-point / n-ellipse guidance
augmentation, DANet / DeepLabV3 segmentation models, data-parallel training,
threshold-swept Jaccard evaluation, checkpointing and experiment logging),
re-designed TPU-first:

* compute path: flax/linen models traced to XLA, ``jax.jit`` / ``pjit`` over a
  ``jax.sharding.Mesh`` (data/model axes) with compiler-inserted collectives —
  replacing the reference's ``torch.nn.DataParallel`` (train_pascal.py:92) and
  its never-finished NCCL/DDP plan (train_pascal.py:1-8);
* input path: host-side numpy/cv2 transform kernels with explicit PRNG,
  per-host sharded loading (the reference's missing "distributed sampler");
* checkpoint/eval/logging subsystems the reference only sketched.

Subpackages
-----------
``data``      dataset, transforms, guidance-map synthesis, loader
``models``    ResNet backbones, DeepLabV3 and DANet heads
``ops``       losses, metrics, attention primitives
``parallel``  mesh construction, shardings, the pjit train step
``train``     trainer loop, optimizer factory, checkpointing, evaluation
``utils``     array helpers, logging, debug asserts, profiling
"""

__version__ = "0.1.0"
