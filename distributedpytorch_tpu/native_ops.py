"""ctypes bindings for the native host-kernel library (native/image_ops.cpp).

The reference consumed native code only through OpenCV (cv2.resize /
cv2.warpAffine / cv2.flip inside its transforms — SURVEY.md §2, "Language
note"); this module is the framework-owned replacement: the same hot
per-sample CPU ops as an in-repo C++ library with pinned semantics, loaded
via ctypes (no pybind11 dependency).

Usage: the library auto-loads from ``native/libdptpu_host.so`` if built
(``make -C native``) or from ``$DPTPU_NATIVE_LIB``; :func:`build` compiles it
on demand.  ``available()`` gates every wrapper, so the pure-python/cv2 path
keeps working without a compiler.  Hot rasterizers (``helpers.make_gt``)
dispatch here automatically whenever the library is built — set
``DPTPU_NATIVE=0`` to force the numpy path (:func:`enabled` is that gate);
resize/warp/flip selection lives in :mod:`..imaging` (``DPTPU_IMAGING``).

All wrappers take/return float32 numpy arrays (HW or HWC, C-contiguous).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_LIB_NAME = "libdptpu_host.so"

NEAREST, BILINEAR, BICUBIC = 0, 1, 2

_lib = None


def _candidates():
    env = os.environ.get("DPTPU_NATIVE_LIB")
    if env:
        yield env
    yield os.path.join(_NATIVE_DIR, _LIB_NAME)


def _bind(lib):
    f32p = ctypes.POINTER(ctypes.c_float)
    f64p = ctypes.POINTER(ctypes.c_double)
    i = ctypes.c_int
    f = ctypes.c_float
    lib.resize_f32.argtypes = [f32p, i, i, i, f32p, i, i, i]
    lib.warp_affine_f32.argtypes = [f32p, i, i, i, f32p, i, i, f64p, i, f]
    lib.hflip_f32.argtypes = [f32p, i, i, i, f32p]
    lib.gaussian_hm_f32.argtypes = [f32p, i, i, i, f, f32p]
    lib.nellipse_f32.argtypes = [f32p, i, i, i, f, f32p]
    try:
        lib.crop_resize_f32.argtypes = [f32p, i, i, i, i, i, i, i,
                                        f32p, i, i, i]
        lib.crop_resize_f32.restype = None
    except AttributeError:
        # stale .so from before the fused kernel existed; callers check
        # hasattr and fall back to the two-stage path
        pass
    for fn in (lib.resize_f32, lib.warp_affine_f32, lib.hflip_f32,
               lib.gaussian_hm_f32, lib.nellipse_f32):
        fn.restype = None
    return lib


def load(path: str | None = None):
    """Load (and cache) the shared library; returns None if unavailable."""
    global _lib
    if _lib is not None:
        return _lib
    paths = [path] if path else list(_candidates())
    for p in paths:
        if p and os.path.exists(p):
            _lib = _bind(ctypes.CDLL(p))
            return _lib
    return None


def available() -> bool:
    return load() is not None


def enabled() -> bool:
    """Library built AND not disabled (``DPTPU_NATIVE=0`` forces numpy)."""
    return os.environ.get("DPTPU_NATIVE") != "0" and available()


_build_lock = threading.Lock()


def build(force: bool = False) -> str:
    """Compile the library with the in-repo Makefile; returns its path.

    Thread-safe: loader worker threads may all hit the lazy-build path on
    first use; only one runs make (a concurrent make would let another
    thread CDLL a half-written .so).
    """
    target = os.path.join(_NATIVE_DIR, _LIB_NAME)
    with _build_lock:
        if force or not os.path.exists(target):
            try:
                subprocess.run(
                    ["make", "-C", _NATIVE_DIR] + (["-B"] if force else []),
                    check=True, capture_output=True, text=True)
            except subprocess.CalledProcessError as e:
                raise RuntimeError(
                    f"native build failed:\n{e.stderr}") from e
        global _lib
        _lib = None
        load(target)
    return target


def _prep(arr: np.ndarray) -> tuple[np.ndarray, int, int, int, bool]:
    """-> (contiguous f32 array, h, w, c, had_channel_dim)."""
    a = np.ascontiguousarray(arr, dtype=np.float32)
    if a.ndim == 2:
        h, w = a.shape
        return a, h, w, 1, False
    if a.ndim == 3:
        h, w, c = a.shape
        return a, h, w, c, True
    raise ValueError(f"expected HW or HWC array, got shape {arr.shape}")


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def resize(arr: np.ndarray, size: tuple[int, int],
           mode: int = BILINEAR) -> np.ndarray:
    """Resize to (H, W) with nearest/bilinear/bicubic (cv2 conventions)."""
    lib = load()
    a, h, w, c, chan = _prep(arr)
    dh, dw = size
    out = np.empty((dh, dw, c), np.float32)
    lib.resize_f32(_ptr(a), h, w, c, _ptr(out), dh, dw, mode)
    return out if chan else out[..., 0]


def warp_affine(arr: np.ndarray, m: np.ndarray, size: tuple[int, int],
                mode: int = BICUBIC, border: float = 0.0) -> np.ndarray:
    """cv2.warpAffine-convention warp: ``m`` is the 2x3 forward matrix."""
    lib = load()
    a, h, w, c, chan = _prep(arr)
    dh, dw = size
    m64 = np.ascontiguousarray(m, dtype=np.float64).reshape(6)
    out = np.empty((dh, dw, c), np.float32)
    lib.warp_affine_f32(_ptr(a), h, w, c, _ptr(out), dh, dw,
                        m64.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                        mode, border)
    return out if chan else out[..., 0]


def has_crop_resize() -> bool:
    lib = load()
    return lib is not None and hasattr(lib, "crop_resize_f32")


def crop_resize(arr: np.ndarray, bbox, size: tuple[int, int],
                mode: int = BICUBIC) -> np.ndarray:
    """Fused crop-to-bbox + resize: the inclusive window ``bbox``
    (x0, y0, x1, y1; may extend beyond the image — the overhang reads 0,
    the zero-pad crop convention) resized to ``size`` without materializing
    the intermediate crop."""
    lib = load()
    a, h, w, c, chan = _prep(arr)
    x0, y0, x1, y1 = (int(v) for v in bbox)
    dh, dw = size
    out = np.empty((dh, dw, c), np.float32)
    lib.crop_resize_f32(_ptr(a), h, w, c, x0, y0, x1, y1,
                        _ptr(out), dh, dw, mode)
    return out if chan else out[..., 0]


def hflip(arr: np.ndarray) -> np.ndarray:
    lib = load()
    a, h, w, c, chan = _prep(arr)
    out = np.empty_like(a).reshape(h, w, c)
    lib.hflip_f32(_ptr(a), h, w, c, _ptr(out))
    return out if chan else out[..., 0]


def gaussian_hm(points_xy, size: tuple[int, int],
                sigma: float = 10.0) -> np.ndarray:
    """Max-combined FWHM-``sigma`` gaussian bumps (helpers.make_gt)."""
    lib = load()
    pts = np.ascontiguousarray(points_xy, dtype=np.float32).reshape(-1, 2)
    h, w = size
    out = np.empty((h, w), np.float32)
    lib.gaussian_hm_f32(_ptr(pts), pts.shape[0], h, w, float(sigma),
                        _ptr(out))
    return out


def nellipse(points_xy, size: tuple[int, int],
             softness: float = 0.05) -> np.ndarray:
    """Soft n-ellipse indicator (guidance.compute_nellipse)."""
    lib = load()
    pts = np.ascontiguousarray(points_xy, dtype=np.float32).reshape(-1, 2)
    h, w = size
    out = np.empty((h, w), np.float32)
    lib.nellipse_f32(_ptr(pts), pts.shape[0], h, w, float(softness),
                     _ptr(out))
    return out
