"""Host imaging backend: one API over OpenCV or the in-repo native library.

Every host-side image op the pipeline needs (resize, affine warp, horizontal
flip, rotation-matrix construction) goes through this module.  Two backends:

* ``cv2`` (preferred when importable) — the same C++ the reference leaned on
  (its transforms called cv2.resize/warpAffine/flip directly,
  custom_transforms.py:116-126,186-193,205-215) and the fastest option
  (SIMD + threading);
* ``native`` — the framework's own C++ kernels (native/image_ops.cpp via
  ctypes, see ``native_ops``), semantics pinned to cv2's conventions
  (pixel-center sampling, a=-0.75 bicubic; parity-tested to <=1e-3 on
  [0,255]-scale data).  Makes OpenCV an optional dependency rather than a
  hard one.

Selection: cv2 if available, else native; ``DPTPU_IMAGING=native`` forces
the native backend (parity testing / cv2-free deployments).
"""

from __future__ import annotations

import os

import numpy as np

try:
    import cv2
    _HAVE_CV2 = True
except ImportError:  # pragma: no cover - exercised in cv2-free deployments
    cv2 = None
    _HAVE_CV2 = False

#: interpolation modes (values match native_ops)
NEAREST, LINEAR, CUBIC = 0, 1, 2

_CV2_FLAGS = {} if not _HAVE_CV2 else {
    NEAREST: cv2.INTER_NEAREST,
    LINEAR: cv2.INTER_LINEAR,
    CUBIC: cv2.INTER_CUBIC,
}


def backend() -> str:
    if os.environ.get("DPTPU_IMAGING") == "native":
        return "native"
    return "cv2" if _HAVE_CV2 else "native"


def _native():
    from . import native_ops
    if not native_ops.available():
        native_ops.build()
    return native_ops


def resize(arr: np.ndarray, size: tuple[int, int],
           interp: int = CUBIC) -> np.ndarray:
    """Resize to (H, W)."""
    if backend() == "cv2":
        return cv2.resize(arr, (size[1], size[0]),
                          interpolation=_CV2_FLAGS[interp])
    out = _native().resize(arr, size, interp)
    if np.issubdtype(arr.dtype, np.integer):
        # Bicubic overshoots; saturate like cv2 does (astype would wrap).
        info = np.iinfo(arr.dtype)
        out = np.clip(np.rint(out), info.min, info.max)
    return out.astype(arr.dtype) if arr.dtype != np.float32 else out


def warp_affine(arr: np.ndarray, m: np.ndarray, size: tuple[int, int],
                interp: int = CUBIC, border: float = 0.0) -> np.ndarray:
    """Forward-matrix affine warp to (H, W) with constant border."""
    if backend() == "cv2":
        bv = border if arr.ndim == 2 else (border,) * arr.shape[2]
        return cv2.warpAffine(arr, m, (size[1], size[0]),
                              flags=_CV2_FLAGS[interp],
                              borderMode=cv2.BORDER_CONSTANT, borderValue=bv)
    out = _native().warp_affine(arr, m, size, interp, border)
    if np.issubdtype(arr.dtype, np.integer):
        info = np.iinfo(arr.dtype)
        out = np.clip(np.rint(out), info.min, info.max)
    return out.astype(arr.dtype) if arr.dtype != np.float32 else out


def flip_h(arr: np.ndarray) -> np.ndarray:
    """Horizontal (left-right) flip."""
    if backend() == "cv2":
        return cv2.flip(arr, flipCode=1)
    return _native().hflip(arr).astype(arr.dtype, copy=False)


def rotation_matrix(center: tuple[float, float], angle_deg: float,
                    scale: float) -> np.ndarray:
    """2x3 rotation+scale matrix about ``center`` —
    cv2.getRotationMatrix2D semantics (positive angle = counter-clockwise)."""
    if backend() == "cv2":
        return cv2.getRotationMatrix2D(center, angle_deg, scale)
    a = np.deg2rad(angle_deg)
    alpha, beta = scale * np.cos(a), scale * np.sin(a)
    cx, cy = center
    return np.array([
        [alpha, beta, (1 - alpha) * cx - beta * cy],
        [-beta, alpha, beta * cx + (1 - alpha) * cy],
    ], dtype=np.float64)
