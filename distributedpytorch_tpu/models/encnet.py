"""EncNet — context encoding segmentation network (flax.linen, NHWC).

Fifth model family of the zoo.  The reference pulls its models from the
PyTorch-Encoding package (reference train_pascal.py:32 imports
``encoding.models``); EncNet (Zhang et al., CVPR'18 "Context Encoding for
Semantic Segmentation") is that package's namesake model: a learned
codebook over the stage-4 features produces a global scene descriptor
that (a) channel-gates the features (SE-style) and (b) predicts which
classes are present anywhere in the image (the SE-loss auxiliary,
``ops.losses.se_presence_loss``).

TPU-first notes:
* the soft-assignment is pure batched einsum via the expansion
  ``||x - c||^2 = |x|^2 + |c|^2 - 2 x.c`` — (B,N,K) scores go straight
  onto the MXU, no per-codeword loops and no dynamic shapes;
* the aggregation ``e_k = sum_i a_ik (x_i - c_k)`` splits into two
  einsums (``a^T x`` and ``colsum(a) * c``) so the (B,N,K,D) residual
  tensor is never materialized;
* output contract matches the zoo: a tuple of input-resolution logit
  maps primary-first, plus (last) the (B, nclass) SE-presence logits —
  the shared multi-output loss dispatches on ndim, and eval consumes
  ``outputs[0]`` unchanged.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from .deeplab import FCNHead, _resize_bilinear
from .resnet import ResNet, make_norm


class Encoding(nn.Module):
    """Learned residual codebook: (B, N, D) -> (B, D) scene descriptor.

    ``n_codes`` codewords ``c_k`` with per-codeword smoothing ``s_k``:
    assignment ``a_ik = softmax_k(-s_k ||x_i - c_k||^2)``, aggregate
    ``e_k = sum_i a_ik (x_i - c_k)``, then BN+ReLU and mean over k.
    """

    n_codes: int
    norm: Any
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, n, d = x.shape
        std = 1.0 / (self.n_codes * d) ** 0.5
        codewords = self.param(
            "codewords", nn.initializers.uniform(scale=2 * std),
            (self.n_codes, d), jnp.float32)
        codewords = codewords - std  # uniform(-std, std), paper's init
        smoothing = self.param(
            "smoothing", nn.initializers.uniform(scale=1.0),
            (self.n_codes,), jnp.float32)  # uniform(0, 1) ~ paper's |init|
        xf = x.astype(jnp.float32)
        # squared distances by expansion: nothing (B,N,K,D)-sized exists
        x2 = jnp.sum(xf * xf, axis=-1, keepdims=True)          # (B,N,1)
        c2 = jnp.sum(codewords * codewords, axis=-1)           # (K,)
        xc = jnp.einsum("bnd,kd->bnk", xf, codewords)          # (B,N,K)
        dist2 = x2 + c2[None, None, :] - 2.0 * xc
        assign = jax.nn.softmax(-smoothing[None, None, :] * dist2, axis=-1)
        # e_k = sum_i a_ik x_i  -  (sum_i a_ik) c_k
        agg_x = jnp.einsum("bnk,bnd->bkd", assign, xf)
        agg_c = assign.sum(axis=1)[..., None] * codewords[None]
        encoded = agg_x - agg_c                                 # (B,K,D)
        # BN over the CODEWORD axis (features=K, stats over B and D) — the
        # published EncNet normalization geometry (BatchNorm1d over the
        # n_codes aggregates), not feature-axis BN.
        encoded = self.norm(name="enc_bn", axis=1)(
            encoded.astype(self.dtype))
        return nn.relu(encoded).mean(axis=1)                    # (B,D)


class EncModule(nn.Module):
    """Context encoding + SE-style channel gate + presence head."""

    channels: int
    nclass: int
    n_codes: int
    norm: Any
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, h, w, c = x.shape
        enc = Encoding(n_codes=self.n_codes, norm=self.norm,
                       dtype=self.dtype, name="encoding")(
            x.reshape(b, h * w, c))
        gate = nn.sigmoid(nn.Dense(self.channels, dtype=self.dtype,
                                   name="fc_gate")(enc))
        gated = x * gate[:, None, None, :]
        se_logits = nn.Dense(self.nclass, dtype=self.dtype,
                             name="fc_se")(enc).astype(jnp.float32)
        return gated, se_logits


class EncNetHead(nn.Module):
    """conv-in -> EncModule gate -> dropout -> classifier (+ SE logits)."""

    nclass: int
    norm: Any
    n_codes: int = 32
    dtype: jnp.dtype = jnp.float32
    dropout_rate: float = 0.1

    @nn.compact
    def __call__(self, x, train: bool = False):
        inter = max(x.shape[-1] // 4, 1)  # 2048 -> 512
        y = nn.Conv(inter, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype, name="in_conv")(x)
        y = self.norm(name="in_bn")(y)
        y = nn.relu(y)
        y, se_logits = EncModule(channels=inter, nclass=self.nclass,
                                 n_codes=self.n_codes, norm=self.norm,
                                 dtype=self.dtype, name="enc")(y)
        y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        logits = nn.Conv(self.nclass, (1, 1), dtype=self.dtype,
                         name="cls")(y)
        return logits, se_logits


class EncNet(nn.Module):
    """Backbone + context-encoding head.

    ``__call__(x, train)`` returns ``(logits, [aux_logits,] se_logits)``:
    input-resolution maps first (the zoo's tuple contract, reference
    train_pascal.py:258-260), the (B, nclass) presence vector last — the
    multi-output loss applies softmax CE to the maps and the EncNet
    SE-presence BCE to the vector (``parallel/step.py:_compute_loss``).
    """

    nclass: int = 21
    backbone_depth: int = 101
    output_stride: int = 8
    n_codes: int = 32
    aux_head: bool = False
    dtype: jnp.dtype = jnp.float32
    bn_cross_replica_axis: str | None = None
    bn_fp32_stats: bool = True  # False: BN stats in compute dtype (see make_norm)
    remat: bool = False
    remat_policy: str | None = None  # jax.checkpoint_policies name (see ResNet)

    @nn.compact
    def __call__(self, x, train: bool = False):
        size = x.shape[1:3]
        feats = ResNet(
            depth=self.backbone_depth,
            output_stride=self.output_stride,
            dtype=self.dtype,
            bn_cross_replica_axis=self.bn_cross_replica_axis,
            bn_fp32_stats=self.bn_fp32_stats,
            remat=self.remat,
            remat_policy=self.remat_policy,
            name="backbone",
        )(x, train=train)
        norm = make_norm(train, self.dtype, self.bn_cross_replica_axis,
                 fp32_stats=self.bn_fp32_stats)
        logits, se_logits = EncNetHead(
            nclass=self.nclass, norm=norm, n_codes=self.n_codes,
            dtype=self.dtype, name="head")(feats["c4"], train=train)
        outs = [_resize_bilinear(logits, size)]
        if self.aux_head:
            aux = FCNHead(nclass=self.nclass, norm=norm, dtype=self.dtype,
                          name="aux_head")(feats["c3"], train=train)
            outs.append(_resize_bilinear(aux, size))
        return (*outs, se_logits)
