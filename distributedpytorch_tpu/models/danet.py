"""DANet — dual attention network segmentation head (flax.linen, NHWC).

The reference's flagship model: ``DANet(1, 'resnet101')`` from PyTorch-Encoding
(reference train_pascal.py:32,86), a dilated ResNet backbone with two parallel
attention branches over the stage-4 features — position attention (full
self-attention over spatial tokens) and channel attention (gram-matrix over
channels) — whose fused sum plus the two branch predictions form a 3-tuple
output, all three supervised by the weighted multi-loss
(train_pascal.py:119,199) and the branch maps visualized as eval panels
(train_pascal.py:258-275).

TPU-first choices:
* the attention math is the batched-einsum primitives in ``ops.attention``
  (MXU-friendly; optionally the blocked online-softmax form so the token-pair
  score matrix never hits HBM at large crops);
* heads predict at output_stride resolution; logits are bilinearly resized to
  input size *inside* the model (jax.image.resize — static shapes, XLA-fused),
  so the loss/metric see input-resolution maps exactly like the reference's
  upsampled outputs;
* with ``nclass=1`` the output is a single-logit sigmoid head — the
  reference's binary interactive-segmentation configuration (evidence: the
  manual sigmoid at train_pascal.py:262,284).
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..ops.attention import (
    blocked_position_attention,
    channel_attention,
    position_attention,
)
from .resnet import ResNet, make_norm


#: 'auto' switch point for the position branch outside the bf16-TPU hot
#: path (scripts/pam_crossover.py on the v5e, table in BASELINE.md): the
#: f32 sweep measured XLA's fused einsum FASTER at every compilable token
#: count (32k: 147 ms vs flash's 185 ms fwd+bwd), so for f32 compute —
#: and on CPU meshes, which run pallas through the slow interpreter —
#: 'auto' keeps einsum while the N^2 scores fit HBM and switches to
#: flash only for memory feasibility: at 64k tokens the N^2 f32 score
#: matrix alone is ~17 GB > v5e HBM.  Under BF16 COMPUTE ON TPU 'auto'
#: is simply flash: the fused VMEM schedule is the default hot path of
#: the mixed-precision regime (model.attention_impl + train.precision,
#: ROADMAP item 4 — the default flip is the bf16-era call; the f32
#: verdict stands).
AUTO_FLASH_MIN_TOKENS = 65536


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def _auto_wants_flash(dtype) -> bool:
    """'auto' promotes the fused Pallas kernels only on TPU and only for
    bf16 compute — see :data:`AUTO_FLASH_MIN_TOKENS`: the f32 crossover
    sweep still favors XLA's einsum, so an f32 run (reference parity,
    ``train.precision=float32``) keeps the measured-faster form."""
    return _on_tpu() and jnp.dtype(dtype) == jnp.dtype(jnp.bfloat16)


def _resize_bilinear(x: jax.Array, size: tuple[int, int]) -> jax.Array:
    """Bilinear NHWC resize to (H, W) — static-shape, differentiable."""
    b, _, _, c = x.shape
    return jax.image.resize(x, (b, *size, c), method="bilinear").astype(x.dtype)


class PositionAttentionModule(nn.Module):
    """Spatial self-attention with a learned zero-init residual gate."""

    channels: int
    norm: Any
    dtype: jnp.dtype = jnp.float32
    block_size: int | None = None  # None -> full attention
    impl: str = "einsum"           # auto | einsum | flash | ring
    sp_mesh: Any = None            # ring: mesh to shard the token axis over
    sp_axis: str = "model"         # ring: mesh axis carrying the tokens
    score_dtype: Any = None        # einsum: dtype the N x N scores are
                                   # materialized in (bf16 halves the HBM
                                   # round trip; softmax math stays f32).
                                   # flash/ring/blocked never materialize
                                   # the N x N matrix — no-op there.

    @nn.compact
    def __call__(self, x):
        b, h, w, c = x.shape
        conv = partial(nn.Conv, use_bias=True, dtype=self.dtype)
        q = conv(self.channels // 8, (1, 1), name="query")(x).reshape(b, h * w, -1)
        k = conv(self.channels // 8, (1, 1), name="key")(x).reshape(b, h * w, -1)
        v = conv(self.channels, (1, 1), name="value")(x).reshape(b, h * w, -1)
        impl = self.impl
        if impl == "auto":
            # bf16 compute on TPU: the fused Pallas kernel IS the hot
            # path.  Otherwise (f32 — where einsum measured faster at
            # every compilable count — or CPU meshes, which run pallas
            # through the interpreter): einsum while the N^2 scores fit
            # HBM, flash beyond (where einsum cannot run at all) — see
            # AUTO_FLASH_MIN_TOKENS.  Backend, dtype and token count
            # are static at trace time: a compile-time choice, one
            # program per shape.
            if _auto_wants_flash(self.dtype):
                impl = "flash"
            else:
                impl = "einsum" if h * w < AUTO_FLASH_MIN_TOKENS \
                    else "flash"
        if impl == "flash":
            from ..ops.pallas_attention import flash_position_attention
            blk = self.block_size or 256
            out = flash_position_attention(q, k, v, blk, blk)
        elif impl == "ring":
            # Sequence parallelism live in the model: the spatial-token axis
            # is sharded over ``sp_axis`` and attention runs as a ppermute
            # ring (parallel/ring.py) — each device holds N/axis tokens and
            # no full N x N score matrix exists on any chip.  Requires
            # h*w % axis_size == 0 (and batch % data-axis == 0 when the
            # mesh also has a data axis).
            if self.sp_mesh is None:
                raise ValueError("impl='ring' needs sp_mesh (the mesh whose "
                                 f"'{self.sp_axis}' axis shards the tokens)")
            from ..parallel.mesh import DATA_AXIS
            from ..parallel.ring import make_ring_attention_inline

            sizes = dict(zip(self.sp_mesh.axis_names,
                             self.sp_mesh.devices.shape))
            if (h * w) % sizes[self.sp_axis]:
                raise ValueError(
                    f"impl='ring' needs the token count ({h}*{w}={h * w}) "
                    f"divisible by the '{self.sp_axis}' axis size "
                    f"({sizes[self.sp_axis]})")
            # Shard the batch over the data axis only when it divides (the
            # init dummy batch is 1 and must stay replicated).
            batch_ax = (DATA_AXIS if sizes.get(DATA_AXIS, 1) > 1
                        and b % sizes[DATA_AXIS] == 0 else None)
            ring = make_ring_attention_inline(
                self.sp_mesh, self.sp_axis, batch_axis=batch_ax)
            out = ring(q, k, v)
        elif impl == "einsum":
            if self.block_size is None:
                out = position_attention(q, k, v,
                                         score_dtype=self.score_dtype)
            else:
                out = blocked_position_attention(q, k, v, self.block_size)
        else:
            raise ValueError(
                f"unknown attention impl: {self.impl!r} "
                "(auto | einsum | flash | ring)")
        out = out.reshape(b, h, w, self.channels)
        # Residual gate starts at 0: the module is an identity at init and
        # learns how much attention context to blend in.
        gamma = self.param("gamma", nn.initializers.zeros, (), jnp.float32)
        return gamma.astype(x.dtype) * out + x


class ChannelAttentionModule(nn.Module):
    """Channel gram-matrix attention with a learned zero-init residual gate.

    ``impl``: ``einsum`` (XLA, reference parity) | ``flash`` (the fused
    Pallas gram+softmax kernel, ops.pallas_attention) | ``auto`` (flash
    for bf16 compute on TPU — the mixed-precision hot path — einsum
    elsewhere, including f32 TPU runs, matching the position branch's
    measured crossover verdict).  Parameter-free either way, so the
    impl choice never touches checkpoints.
    """

    dtype: jnp.dtype = jnp.float32
    impl: str = "einsum"           # auto | einsum | flash
    block_size: int | None = None  # flash: token-block rows per VMEM tile

    @nn.compact
    def __call__(self, x):
        b, h, w, c = x.shape
        impl = self.impl
        if impl == "auto":
            impl = "flash" if _auto_wants_flash(self.dtype) else "einsum"
        tokens = x.reshape(b, h * w, c)
        if impl == "flash":
            from ..ops.pallas_attention import flash_channel_attention
            out = flash_channel_attention(tokens, self.block_size or 256)
        elif impl == "einsum":
            out = channel_attention(tokens)
        else:
            raise ValueError(f"unknown channel-attention impl: "
                             f"{self.impl!r} (auto | einsum | flash)")
        out = out.reshape(b, h, w, c)
        gamma = self.param("gamma", nn.initializers.zeros, (), jnp.float32)
        return gamma.astype(x.dtype) * out + x


class DANetHead(nn.Module):
    """Dual-attention head: conv-in -> {PAM, CAM} -> conv-out -> 3 classifiers.

    Returns ``(fused_logits, pam_logits, cam_logits)`` at feature resolution.
    """

    nclass: int
    norm: Any
    dtype: jnp.dtype = jnp.float32
    pam_block_size: int | None = None
    pam_impl: str = "einsum"
    pam_sp_mesh: Any = None
    pam_sp_axis: str = "model"
    pam_score_dtype: Any = None
    cam_impl: str = "einsum"
    dropout_rate: float = 0.1
    moe_experts: int = 0        # >0: MoE FFN on the fused features
    moe_hidden: int | None = None
    moe_k: int = 1
    moe_capacity_factor: float = 1.25

    @nn.compact
    def __call__(self, x, train: bool = False):
        inter = max(x.shape[-1] // 4, 1)  # 2048 -> 512
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)

        def conv_bn_relu(y, name):
            y = conv(inter, (3, 3), padding="SAME", name=f"{name}_conv")(y)
            y = self.norm(name=f"{name}_bn")(y)
            return nn.relu(y)

        def classifier(y, name):
            y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
            return nn.Conv(self.nclass, (1, 1), dtype=self.dtype,
                           name=f"{name}_cls")(y)

        pa = conv_bn_relu(x, "pam_in")
        pa = PositionAttentionModule(
            channels=inter, norm=self.norm, dtype=self.dtype,
            block_size=self.pam_block_size, impl=self.pam_impl,
            sp_mesh=self.pam_sp_mesh, sp_axis=self.pam_sp_axis,
            score_dtype=self.pam_score_dtype,
            name="pam")(pa)
        pa = conv_bn_relu(pa, "pam_out")

        ca = conv_bn_relu(x, "cam_in")
        ca = ChannelAttentionModule(dtype=self.dtype, impl=self.cam_impl,
                                    name="cam")(ca)
        ca = conv_bn_relu(ca, "cam_out")

        fused = pa + ca
        if self.moe_experts > 0:
            # Sparse capacity on the fused features: each spatial token is
            # routed to 1/E of the FFN params.  Under the trainer's
            # `mesh.shard_params=true`, tp_param_specs shards these expert
            # stacks one-group-per-device over the model axis (expert
            # parallelism in the flagship step); otherwise they replicate
            # like any other params.  The standalone EP path is
            # `make_moe_apply`/`make_expert_mesh` in parallel/moe.py.
            # MoEMlp keeps the residual, so dropped tokens pass through,
            # and sows the load-balancing aux loss for the train step.
            from ..parallel.moe import MoEMlp

            b, h, w, c = fused.shape
            tokens = fused.astype(jnp.float32).reshape(b, h * w, c)
            tokens = MoEMlp(
                n_experts=self.moe_experts,
                hidden=self.moe_hidden or c,
                k=self.moe_k,
                capacity_factor=self.moe_capacity_factor,
                name="moe")(tokens)
            fused = tokens.reshape(b, h, w, c).astype(fused.dtype)
        return (
            classifier(fused, "fused"),
            classifier(pa, "pam"),
            classifier(ca, "cam"),
        )


class DANet(nn.Module):
    """Backbone + dual-attention head; ``__call__(x, train)`` -> 3-tuple of
    input-resolution logit maps, matching the reference model's output
    contract (tuple indexing at reference train_pascal.py:258-260).

    ``guidance_inject`` picks where the click-guidance channel (the LAST
    input channel, reference custom_transforms.py ConcatInputs) enters:

    * ``'stem'`` (default, reference parity): the backbone consumes the
      full RGB+guidance concat — every click pays the whole forward.
    * ``'head'``: the backbone consumes only the RGB channels and the
      guidance channel joins at the head via a zero-init 1x1 projection
      added to the c4 features — making the backbone encoding a pure
      function of the image.  This is the session-serving architecture:
      ``stage='encode'`` (image -> c4 features, ~90% of the FLOPs) is
      computed once per interactive session, ``stage='decode'``
      (features + guidance -> logits) once per refinement click
      (serve/sessions.py).  Zero-init keeps the module's residual-gate
      idiom: at init the guidance is a no-op and training learns how
      much to blend in.

    Staged calls (``guidance_inject='head'`` only; ``stage`` is a static
    Python string, so each stage traces its own program):

    * ``stage='encode'``: ``x`` is the RGB crop (B, H, W, C-1); returns
      the c4 feature map (B, H/os, W/os, C_feat).
    * ``stage='decode'``: ``x`` is ``(features, guidance)`` with
      guidance (B, H, W, 1) in crop space; ``out_size`` (static) is the
      logit-map resolution (the full path's input size).
    """

    nclass: int = 1
    backbone_depth: int = 101
    output_stride: int = 8
    dtype: jnp.dtype = jnp.float32
    bn_cross_replica_axis: str | None = None
    bn_fp32_stats: bool = True  # False: BN stats in compute dtype (see make_norm)
    pam_block_size: int | None = None
    pam_impl: str = "einsum"  # auto | einsum | flash | ring (seq-parallel)
    pam_sp_mesh: Any = None   # ring: mesh whose axis shards the tokens
    pam_sp_axis: str = "model"
    pam_score_dtype: Any = None  # einsum: N x N score materialization dtype
    cam_impl: str = "einsum"  # auto | einsum | flash (fused Pallas gram)
    remat: bool = False
    remat_policy: str | None = None  # jax.checkpoint_policies name (see ResNet)
    moe_experts: int = 0      # >0: MoE FFN in the head (see DANetHead)
    moe_hidden: int | None = None
    moe_k: int = 1
    moe_capacity_factor: float = 1.25
    guidance_inject: str = "stem"  # stem | head (encode/decode split)

    def _encode(self, x, train: bool):
        """Backbone features — the session-invariant stage."""
        return ResNet(
            depth=self.backbone_depth,
            output_stride=self.output_stride,
            dtype=self.dtype,
            bn_cross_replica_axis=self.bn_cross_replica_axis,
            bn_fp32_stats=self.bn_fp32_stats,
            remat=self.remat,
            remat_policy=self.remat_policy,
            name="backbone",
        )(x, train=train)["c4"]

    def _decode(self, feats, guidance, out_size: tuple[int, int],
                train: bool):
        """Head on (optionally guidance-conditioned) c4 features."""
        if guidance is not None:
            g = _resize_bilinear(guidance.astype(self.dtype),
                                 feats.shape[1:3])
            feats = feats + nn.Conv(
                feats.shape[-1], (1, 1), use_bias=False, dtype=self.dtype,
                kernel_init=nn.initializers.zeros,
                name="guidance_proj")(g)
        norm = make_norm(train, self.dtype, self.bn_cross_replica_axis,
                         fp32_stats=self.bn_fp32_stats)
        outs = DANetHead(
            nclass=self.nclass,
            norm=norm,
            dtype=self.dtype,
            pam_block_size=self.pam_block_size,
            pam_impl=self.pam_impl,
            pam_sp_mesh=self.pam_sp_mesh,
            pam_sp_axis=self.pam_sp_axis,
            pam_score_dtype=self.pam_score_dtype,
            cam_impl=self.cam_impl,
            moe_experts=self.moe_experts,
            moe_hidden=self.moe_hidden,
            moe_k=self.moe_k,
            moe_capacity_factor=self.moe_capacity_factor,
            name="head",
        )(feats, train=train)
        return tuple(_resize_bilinear(o, out_size) for o in outs)

    @nn.compact
    def __call__(self, x, train: bool = False, stage: str = "full",
                 out_size: tuple[int, int] | None = None):
        if self.guidance_inject not in ("stem", "head"):
            raise ValueError(f"unknown guidance_inject: "
                             f"{self.guidance_inject!r} (stem | head)")
        if stage == "full":
            size = out_size or x.shape[1:3]
            if self.guidance_inject == "stem":
                return self._decode(self._encode(x, train), None, size,
                                    train)
            # head injection: backbone sees RGB only; the guidance (last)
            # channel re-enters at the head — x stays the SAME concat the
            # stem path consumes, so the loss/eval/serve wire is unchanged
            return self._decode(self._encode(x[..., :-1], train),
                                x[..., -1:], size, train)
        if self.guidance_inject != "head":
            raise ValueError(
                f"stage={stage!r} needs guidance_inject='head' — the stem "
                "architecture folds the guidance into the backbone, so "
                "its encoding cannot be reused across clicks")
        if stage == "encode":
            return self._encode(x, train)
        if stage == "decode":
            if out_size is None:
                raise ValueError("stage='decode' needs out_size (the "
                                 "logit-map resolution)")
            feats, guidance = x
            return self._decode(feats, guidance, tuple(out_size), train)
        raise ValueError(f"unknown stage: {stage!r} "
                         "(full | encode | decode)")
