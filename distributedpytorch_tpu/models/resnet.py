"""ResNet backbones (flax.linen, NHWC) with dilated output-stride control.

TPU-native re-design of the backbone family the reference consumes externally:
``DANet(1, 'resnet101')`` pulls a dilated ResNet-101 from PyTorch-Encoding
(reference train_pascal.py:32,86), modified to a 4-channel stem for the
RGB+guidance input (train_pascal.py:65,133).  Here the stem width is just a
constructor argument, and the dilation schedule is expressed as an
``output_stride`` in {8, 16, 32}: strides that would shrink the feature map
below input/output_stride become dilations instead — the standard dilated-FCN
trick DANet (os=8) and DeepLabV3 (os=16) rely on.

TPU notes:
* NHWC everywhere; convs are ``nn.Conv`` (lax.conv_general_dilated -> MXU).
* BatchNorm is per-replica by default, matching the reference's
  ``sync_bn=False`` (train_pascal.py:85); pass ``bn_cross_replica_axis`` to
  sync batch statistics over a mesh axis instead (``axis_name`` is resolved
  inside pjit/shard_map).
* ``dtype`` is the compute/activation dtype (bf16 for the mixed-precision
  configs); params stay float32.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import jax.numpy as jnp
from flax import linen as nn

ModuleDef = Any

#: block counts per stage
RESNET_DEPTHS = {
    18: (2, 2, 2, 2),
    34: (3, 4, 6, 3),
    50: (3, 4, 6, 3),
    101: (3, 4, 23, 3),
    152: (3, 8, 36, 3),
}
#: depths that use the 3-conv bottleneck block (4x channel expansion)
BOTTLENECK_DEPTHS = (50, 101, 152)


def make_norm(
    train: bool,
    dtype: jnp.dtype = jnp.float32,
    cross_replica_axis: str | None = None,
    momentum: float = 0.9,
    fp32_stats: bool = True,
) -> ModuleDef:
    """BatchNorm factory: per-replica stats by default (the reference's
    ``sync_bn=False``), cross-replica when an axis name is given.

    ``fp32_stats=False`` computes batch statistics in the compute dtype
    instead of flax's float32 promotion (``force_float32_reductions``).
    The op profiles attribute 46% of the b8 flagship's device time — and
    the b16 regression's largest term — to bf16→f32 convert+reduce chains
    riding the conv fusions (BASELINE.md batch autopsy); this is the
    measured-mechanism A/B.  Accuracy: bf16 mean/var over >=8·64² elements
    loses ~2-3 decimal digits; gate on a convergence check before
    defaulting."""
    return partial(
        nn.BatchNorm,
        use_running_average=not train,
        momentum=momentum,
        epsilon=1e-5,
        dtype=dtype,
        axis_name=cross_replica_axis,
        force_float32_reductions=fp32_stats,
    )


class BasicBlock(nn.Module):
    """Two 3x3 convs + identity shortcut (ResNet-18/34)."""

    filters: int
    norm: ModuleDef
    strides: int = 1
    dilation: int = 1
    dtype: jnp.dtype = jnp.float32

    expansion: int = 1

    @nn.compact
    def __call__(self, x):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        residual = x
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides),
                 kernel_dilation=(self.dilation, self.dilation), padding="SAME")(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3),
                 kernel_dilation=(self.dilation, self.dilation), padding="SAME")(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1),
                            strides=(self.strides, self.strides))(residual)
            residual = self.norm()(residual)
        return nn.relu(y + residual)


class BottleneckBlock(nn.Module):
    """1x1 reduce -> 3x3 (carries stride/dilation) -> 1x1 expand x4.

    Stride on the 3x3 (the "v1.5" placement) — the variant dilated
    segmentation backbones use.
    """

    filters: int
    norm: ModuleDef
    strides: int = 1
    dilation: int = 1
    dtype: jnp.dtype = jnp.float32

    expansion: int = 4

    @nn.compact
    def __call__(self, x):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        residual = x
        y = conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides),
                 kernel_dilation=(self.dilation, self.dilation), padding="SAME")(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = conv(self.filters * self.expansion, (1, 1))(y)
        # zero-init the last norm's scale: each block starts as identity,
        # stabilizing early training of deep nets
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * self.expansion, (1, 1),
                            strides=(self.strides, self.strides))(residual)
            residual = self.norm()(residual)
        return nn.relu(y + residual)


def _stage_plan(output_stride: int) -> tuple[Sequence[int], Sequence[int]]:
    """(strides, dilations) for stages 1-4 given the target output stride.

    Stride 32 is the classification layout; 16 dilates stage 4; 8 dilates
    stages 3 and 4 (DANet's layout).
    """
    if output_stride == 32:
        return (1, 2, 2, 2), (1, 1, 1, 1)
    if output_stride == 16:
        return (1, 2, 2, 1), (1, 1, 1, 2)
    if output_stride == 8:
        return (1, 2, 1, 1), (1, 1, 2, 4)
    raise ValueError(f"output_stride must be 8, 16 or 32, got {output_stride}")


class ResNet(nn.Module):
    """Dilated ResNet feature extractor.

    ``__call__(x, train)`` -> dict of feature maps ``{'c1','c2','c3','c4'}``
    (stage outputs; ``c4`` is the head input at input/output_stride, ``c3``
    feeds auxiliary heads).  ``x`` is NHWC with any channel count — the stem
    adapts, covering the reference's 4-channel RGB+guidance input.
    """

    depth: int = 50
    output_stride: int = 16
    multi_grid: Sequence[int] | None = None  # stage-4 per-block dilation mult
    width: int = 64
    dtype: jnp.dtype = jnp.float32
    bn_cross_replica_axis: str | None = None
    bn_fp32_stats: bool = True  # False: BN stats in compute dtype (see make_norm)
    deep_stem: bool = False  # 3x 3x3 stem (encoding-style) vs single 7x7
    remat: bool = False  # rematerialize blocks: trade FLOPs for HBM
    #: with remat: a jax.checkpoint_policies name ('dots_saveable',
    #: 'dots_with_no_batch_dims_saveable', ...) instead of full recompute.
    #: Rationale (BASELINE.md b16 autopsy): XLA AUTO-rematerializes under
    #: HBM pressure at b16 with its own op choice; full per-block remat
    #: measured -13.5% there because the recompute re-reads more HBM than
    #: the stash it saves.  'dots_saveable' keeps conv/matmul outputs and
    #: recomputes only the cheap elementwise/BN chains — the explicit
    #: pre-emption VERDICT r3 item 5 asks to A/B.
    remat_policy: str | None = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = make_norm(train, self.dtype, self.bn_cross_replica_axis,
                 fp32_stats=self.bn_fp32_stats)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        block_cls = (
            BottleneckBlock if self.depth in BOTTLENECK_DEPTHS else BasicBlock
        )
        # Explicit block names (matching linen's auto-numbering) keep the
        # param tree identical whether or not remat is on — a checkpoint
        # written either way restores either way.
        block_name = block_cls.__name__
        if self.remat:
            # jax.checkpoint per residual block: the backward pass recomputes
            # each block's activations instead of holding all ~100 of them in
            # HBM — the standard way to fit bigger batches/crops per chip.
            policy = None
            if self.remat_policy:
                import jax

                policy = getattr(jax.checkpoint_policies, self.remat_policy)
            block_cls = nn.remat(block_cls, policy=policy)
        counts = RESNET_DEPTHS[self.depth]
        strides, dilations = _stage_plan(self.output_stride)

        if self.deep_stem:
            for i, (f, s) in enumerate(
                ((self.width, 2), (self.width, 1), (self.width * 2, 1))
            ):
                x = conv(f, (3, 3), strides=(s, s), padding="SAME")(x)
                x = norm()(x)
                x = nn.relu(x)
        else:
            x = conv(self.width, (7, 7), strides=(2, 2), padding="SAME")(x)
            x = norm()(x)
            x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")

        feats = {}
        filters = self.width
        block_idx = 0
        for stage, n_blocks in enumerate(counts):
            for i in range(n_blocks):
                dil = dilations[stage]
                if stage == 3 and self.multi_grid is not None:
                    dil *= self.multi_grid[min(i, len(self.multi_grid) - 1)]
                x = block_cls(
                    filters=filters,
                    norm=norm,
                    strides=strides[stage] if i == 0 else 1,
                    dilation=dil,
                    dtype=self.dtype,
                    name=f"{block_name}_{block_idx}",
                )(x)
                block_idx += 1
            feats[f"c{stage + 1}"] = x
            filters *= 2
        return feats


def resnet50(**kw) -> ResNet:
    return ResNet(depth=50, **kw)


def resnet101(**kw) -> ResNet:
    return ResNet(depth=101, **kw)
