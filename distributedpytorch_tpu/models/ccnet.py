"""CCNet — criss-cross attention segmentation model (flax.linen, NHWC).

Sixth model family of the zoo, and the third member of the reference's own
attention lineage (the reference imports DANet from the PyTorch-Encoding
family, train_pascal.py:32; CCNet — Huang et al. ICCV'19 — is that
lineage's memory-light successor).  Where DANet's position attention
scores every token against every token (N² = (HW)² energies — the
measured 64 MB HBM tenant of the flagship step, BASELINE.md roofline),
criss-cross attention scores each position only against its own row and
column: O(N·(H+W)) energies, with a recurrence of R=2 giving every pixel
a full-image receptive field through (at most) one intermediate
criss-cross hop.

TPU notes: the row/column attentions are two batched einsums with a
softmax over the concatenated (H + W) axis — static shapes, MXU-shaped
contractions, no gathers; XLA fuses the mask/softmax/cast chain.  At the
flagship geometry (64×64 tokens) the energy tensor is 16× smaller than
DANet's N² scores (B·H·W·(H+W) vs B·(HW)²), which is the architectural
answer to the same HBM-bandwidth bound that ``model.pam_score_dtype``
attacks numerically.  The recurrence shares one parameter set (the same
submodule applied R times — the paper's weight-shared RCCA).

Output contract matches the zoo: tuple of input-resolution logit maps,
primary first (+ optional FCN aux head on c3), so the shared multi-output
loss, Trainer, and evaluators drive it unchanged.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax.numpy as jnp
from flax import linen as nn

from .deeplab import FCNHead, _resize_bilinear
from .resnet import ResNet, make_norm


class CrissCrossAttention(nn.Module):
    """One criss-cross attention step: each position attends over its row
    and column; residual-gated like the DANet heads (gamma init 0).

    The column branch's self-energy is masked to -inf so the position
    itself is counted exactly once (it stays visible through the row
    branch) — the official implementation's INF trick, done with a static
    boolean eye instead of an additive INF tensor.
    """

    reduction: int = 8
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, h, w, c = x.shape
        qk_c = max(c // self.reduction, 1)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        q = conv(qk_c, (1, 1), name="query")(x)
        k = conv(qk_c, (1, 1), name="key")(x)
        v = conv(c, (1, 1), name="value")(x)

        # energies: column (same w, over all i') and row (same h, over all
        # j') — two MXU contractions, no N x N matrix ever exists
        e_col = jnp.einsum("bijc,bkjc->bijk", q, k)        # (B,H,W,H)
        e_row = jnp.einsum("bijc,bikc->bijk", q, k)        # (B,H,W,W)
        # mask the column self (k == i): counted once via the row branch
        self_mask = jnp.eye(h, dtype=bool)[:, None, :]     # (H,1,H)
        neg = jnp.asarray(jnp.finfo(jnp.float32).min / 2, e_col.dtype)
        e_col = jnp.where(self_mask, neg, e_col)

        # softmax over the concatenated (H + W) criss-cross neighborhood,
        # in f32 (bf16 energies would collapse near-ties; cast back after)
        att = nn.softmax(
            jnp.concatenate([e_col, e_row], axis=-1).astype(jnp.float32),
            axis=-1).astype(self.dtype)
        a_col, a_row = att[..., :h], att[..., h:]

        out = (jnp.einsum("bijk,bkjc->bijc", a_col, v)
               + jnp.einsum("bijk,bikc->bijc", a_row, v))
        gamma = self.param("gamma", nn.initializers.zeros, ())
        return x + gamma.astype(self.dtype) * out


class RCCAHead(nn.Module):
    """The paper's RCCA module: 3x3 reduce -> R weight-shared criss-cross
    steps -> 3x3 project -> concat with the input -> bottleneck+dropout."""

    channels: int
    recurrence: int
    norm: Any
    dtype: jnp.dtype = jnp.float32
    dropout_rate: float = 0.1

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)

        def cbr(y, ch, kernel, name):
            y = conv(ch, kernel, padding="SAME", name=f"{name}_conv")(y)
            y = self.norm(name=f"{name}_bn")(y)
            return nn.relu(y)

        y = cbr(x, self.channels, (3, 3), "reduce")
        cca = CrissCrossAttention(dtype=self.dtype, name="cca")
        for _ in range(self.recurrence):   # same module -> shared params
            y = cca(y)
        y = cbr(y, self.channels, (3, 3), "project")
        y = jnp.concatenate([x, y], axis=-1)
        y = cbr(y, self.channels, (3, 3), "bottleneck")
        return nn.Dropout(self.dropout_rate, deterministic=not train)(y)


class CCNet(nn.Module):
    """Dilated ResNet + recurrent criss-cross attention head;
    ``__call__(x, train)`` -> (logits,) or (logits, aux_logits) at input
    resolution."""

    nclass: int = 21
    backbone_depth: int = 101
    output_stride: int = 8
    head_channels: int = 512
    recurrence: int = 2          # R=2: full-image receptive field
    aux_head: bool = False
    dtype: jnp.dtype = jnp.float32
    bn_cross_replica_axis: str | None = None
    bn_fp32_stats: bool = True  # False: BN stats in compute dtype (see make_norm)
    remat: bool = False
    remat_policy: str | None = None  # jax.checkpoint_policies name (see ResNet)

    @nn.compact
    def __call__(self, x, train: bool = False):
        size = x.shape[1:3]
        feats = ResNet(
            depth=self.backbone_depth,
            output_stride=self.output_stride,
            dtype=self.dtype,
            bn_cross_replica_axis=self.bn_cross_replica_axis,
            bn_fp32_stats=self.bn_fp32_stats,
            remat=self.remat,
            remat_policy=self.remat_policy,
            name="backbone",
        )(x, train=train)
        norm = make_norm(train, self.dtype, self.bn_cross_replica_axis,
                 fp32_stats=self.bn_fp32_stats)
        y = RCCAHead(channels=self.head_channels,
                     recurrence=self.recurrence, norm=norm,
                     dtype=self.dtype, name="rcca")(feats["c4"], train=train)
        y = nn.Conv(self.nclass, (1, 1), dtype=self.dtype,
                    name="classifier")(y)
        outs = [_resize_bilinear(y, size)]
        if self.aux_head:
            aux = FCNHead(nclass=self.nclass, norm=norm, dtype=self.dtype,
                          name="aux")(feats["c3"], train=train)
            outs.append(_resize_bilinear(aux, size))
        return tuple(outs)
