"""DeepLabV3 (ASPP) segmentation model (flax.linen, NHWC).

The second model family: the reference driver carries a commented DeepLab
alternative to DANet (reference train_pascal.py:85), and BASELINE.md's
measured configs name DeepLabV3-ResNet50/101 at output_stride 16 as the
metric-bearing model.  Built natively: atrous spatial pyramid pooling over the
dilated-ResNet stage-4 features, image-level pooling branch, optional FCN
auxiliary head on stage-3 (standard DeepLabV3 training recipe).

Output contract mirrors the framework-wide convention: a tuple of
input-resolution logit maps, primary first — so the same multi-output loss
(``ops.multi_output_loss`` / the reference's ``SegmentationMultiLosses``
semantics) and trainer drive either model family unchanged.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

from .resnet import ResNet, make_norm


def _resize_bilinear(x: jax.Array, size: tuple[int, int]) -> jax.Array:
    b, _, _, c = x.shape
    return jax.image.resize(x, (b, *size, c), method="bilinear").astype(x.dtype)


class ASPP(nn.Module):
    """Atrous spatial pyramid pooling: parallel 1x1 + three dilated 3x3
    branches + global-pool branch, concatenated and projected."""

    channels: int
    rates: Sequence[int]
    norm: Any
    dtype: jnp.dtype = jnp.float32
    dropout_rate: float = 0.5

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)

        def branch(y, kernel, rate, name):
            y = conv(self.channels, kernel,
                     kernel_dilation=(rate, rate), padding="SAME",
                     name=f"{name}_conv")(y)
            y = self.norm(name=f"{name}_bn")(y)
            return nn.relu(y)

        outs = [branch(x, (1, 1), 1, "b0")]
        for i, r in enumerate(self.rates):
            outs.append(branch(x, (3, 3), r, f"b{i + 1}"))

        # Image-level pooling branch: global mean -> 1x1 -> broadcast back.
        pooled = x.mean(axis=(1, 2), keepdims=True)
        pooled = branch(pooled, (1, 1), 1, "pool")
        outs.append(jnp.broadcast_to(pooled, x.shape[:3] + (self.channels,)))

        y = jnp.concatenate(outs, axis=-1)
        y = branch(y, (1, 1), 1, "project")
        return nn.Dropout(self.dropout_rate, deterministic=not train)(y)


class FCNHead(nn.Module):
    """3x3 conv-bn-relu + dropout + 1x1 classifier (auxiliary supervision)."""

    nclass: int
    norm: Any
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        inter = max(x.shape[-1] // 4, 1)
        y = nn.Conv(inter, (3, 3), use_bias=False, padding="SAME",
                    dtype=self.dtype)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = nn.Dropout(0.1, deterministic=not train)(y)
        return nn.Conv(self.nclass, (1, 1), dtype=self.dtype)(y)


class DecoderV3Plus(nn.Module):
    """DeepLabV3+ decoder: ASPP features upsampled to stride 4 and fused
    with 1x1-projected low-level (c1) features, refined by two 3x3 convs.

    Recovers the object-boundary detail the os=16 encoder path loses —
    the standard accuracy upgrade over plain V3 at the same encoder cost."""

    channels: int
    low_channels: int
    norm: Any
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, y, low, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, padding="SAME",
                       dtype=self.dtype)
        low = conv(self.low_channels, (1, 1), name="low_proj")(low)
        low = self.norm(name="low_bn")(low)
        low = nn.relu(low)
        y = _resize_bilinear(y, low.shape[1:3])
        y = jnp.concatenate([y, low], axis=-1)
        for i in range(2):
            y = conv(self.channels, (3, 3), name=f"refine{i}_conv")(y)
            y = self.norm(name=f"refine{i}_bn")(y)
            y = nn.relu(y)
        return y


class FCN(nn.Module):
    """Fully-convolutional network (Long et al., CVPR'15, the torchvision
    ``fcn_resnet50/101`` structure): dilated ResNet + FCNHead on c4,
    bilinear upsample to input resolution.  ``__call__(x, train)`` ->
    (logits,) or (logits, aux_logits).

    The smallest member of the model zoo — same backbone (so torchvision's
    ImageNet checkpoints warm-start it via ``checkpoint.warm_start``), no
    ASPP/attention context module; the accuracy-per-FLOP baseline the
    fancier heads are judged against."""

    nclass: int = 21
    backbone_depth: int = 50
    output_stride: int = 8     # torchvision dilates stages 3+4
    aux_head: bool = False
    dtype: jnp.dtype = jnp.float32
    bn_cross_replica_axis: str | None = None
    bn_fp32_stats: bool = True  # False: BN stats in compute dtype (see make_norm)
    remat: bool = False
    remat_policy: str | None = None  # jax.checkpoint_policies name (see ResNet)

    @nn.compact
    def __call__(self, x, train: bool = False):
        size = x.shape[1:3]
        feats = ResNet(
            depth=self.backbone_depth,
            output_stride=self.output_stride,
            dtype=self.dtype,
            bn_cross_replica_axis=self.bn_cross_replica_axis,
            bn_fp32_stats=self.bn_fp32_stats,
            remat=self.remat,
            remat_policy=self.remat_policy,
            name="backbone",
        )(x, train=train)
        norm = make_norm(train, self.dtype, self.bn_cross_replica_axis,
                 fp32_stats=self.bn_fp32_stats)
        y = FCNHead(nclass=self.nclass, norm=norm, dtype=self.dtype,
                    name="head")(feats["c4"], train=train)
        outs = [_resize_bilinear(y, size)]
        if self.aux_head:
            aux = FCNHead(nclass=self.nclass, norm=norm, dtype=self.dtype,
                          name="aux")(feats["c3"], train=train)
            outs.append(_resize_bilinear(aux, size))
        return tuple(outs)


class DeepLabV3(nn.Module):
    """Dilated ResNet + ASPP; ``__call__(x, train)`` -> (logits,) or
    (logits, aux_logits) at input resolution."""

    nclass: int = 21
    backbone_depth: int = 50
    output_stride: int = 16
    aspp_channels: int = 256
    aux_head: bool = False
    decoder: bool = False     # True = DeepLabV3+ (low-level c1 skip fusion)
    dtype: jnp.dtype = jnp.float32
    bn_cross_replica_axis: str | None = None
    bn_fp32_stats: bool = True  # False: BN stats in compute dtype (see make_norm)
    remat: bool = False
    remat_policy: str | None = None  # jax.checkpoint_policies name (see ResNet)

    @nn.compact
    def __call__(self, x, train: bool = False):
        size = x.shape[1:3]
        # ASPP rates scale with dilation: (6,12,18) at os=16, doubled at os=8.
        rates = (6, 12, 18) if self.output_stride == 16 else (12, 24, 36)
        feats = ResNet(
            depth=self.backbone_depth,
            output_stride=self.output_stride,
            multi_grid=(1, 2, 4),
            dtype=self.dtype,
            bn_cross_replica_axis=self.bn_cross_replica_axis,
            bn_fp32_stats=self.bn_fp32_stats,
            remat=self.remat,
            remat_policy=self.remat_policy,
            name="backbone",
        )(x, train=train)
        norm = make_norm(train, self.dtype, self.bn_cross_replica_axis,
                 fp32_stats=self.bn_fp32_stats)
        y = ASPP(channels=self.aspp_channels, rates=rates, norm=norm,
                 dtype=self.dtype, name="aspp")(feats["c4"], train=train)
        if self.decoder:
            y = DecoderV3Plus(channels=self.aspp_channels, low_channels=48,
                              norm=norm, dtype=self.dtype,
                              name="decoder")(y, feats["c1"], train=train)
        y = nn.Conv(self.nclass, (1, 1), dtype=self.dtype, name="classifier")(y)
        outs = [_resize_bilinear(y, size)]
        if self.aux_head:
            aux = FCNHead(nclass=self.nclass, norm=norm, dtype=self.dtype,
                          name="aux")(feats["c3"], train=train)
            outs.append(_resize_bilinear(aux, size))
        return tuple(outs)
