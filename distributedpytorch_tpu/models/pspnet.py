"""PSPNet — pyramid scene parsing segmentation model (flax.linen, NHWC).

Fourth model family of the zoo (alongside DANet — the reference's flagship,
reference train_pascal.py:32,86 — DeepLabV3(+), and FCN): Zhao et al.
CVPR'17's pyramid pooling module over the dilated-ResNet stage-4 features.
Where ASPP samples *dilated convolution* context at multiple rates, PPM
pools the whole feature map to a few fixed grid sizes (1, 2, 3, 6),
projects each, and upsamples back — global context at four granularities
for almost no FLOPs.

TPU notes: the pyramid pooling is average-pooling to *static* tiny grids +
bilinear resize back — all static-shape `jax.image.resize`/`mean` ops that
XLA fuses; no adaptive-pool dynamic shapes.  Output contract matches the
zoo: a tuple of input-resolution logit maps, primary first (+ optional FCN
aux head on c3, the original paper's training recipe), so the shared
multi-output loss and Trainer drive it unchanged.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

from .deeplab import FCNHead, _resize_bilinear
from .resnet import ResNet, make_norm


class PyramidPooling(nn.Module):
    """PPM: pool to each bin grid, 1x1-project to C/len(bins), upsample,
    concat with the input, 3x3-project."""

    channels: int
    bins: Sequence[int]
    norm: Any
    dtype: jnp.dtype = jnp.float32
    dropout_rate: float = 0.1

    @nn.compact
    def __call__(self, x, train: bool = False):
        h, w = x.shape[1:3]
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        branch_c = max(self.channels // len(self.bins), 1)

        def project(y, ch, kernel, name):
            y = conv(ch, kernel, padding="SAME", name=f"{name}_conv")(y)
            y = self.norm(name=f"{name}_bn")(y)
            return nn.relu(y)

        outs = [x]
        for bin_ in self.bins:
            # Static-grid average pool: reshape-mean when the grid divides,
            # else resize-based pooling (still static shapes).
            if h % bin_ == 0 and w % bin_ == 0:
                b, _, _, c = x.shape
                pooled = x.reshape(b, bin_, h // bin_, bin_, w // bin_, c) \
                    .mean(axis=(2, 4))
            else:
                pooled = jax.image.resize(
                    x, (x.shape[0], bin_, bin_, x.shape[-1]),
                    method="linear").astype(x.dtype)
            pooled = project(pooled, branch_c, (1, 1), f"bin{bin_}")
            outs.append(_resize_bilinear(pooled, (h, w)))

        y = jnp.concatenate(outs, axis=-1)
        y = project(y, self.channels, (3, 3), "fuse")
        return nn.Dropout(self.dropout_rate, deterministic=not train)(y)


class PSPNet(nn.Module):
    """Dilated ResNet + pyramid pooling; ``__call__(x, train)`` ->
    (logits,) or (logits, aux_logits) at input resolution."""

    nclass: int = 21
    backbone_depth: int = 50
    output_stride: int = 8      # the paper trains at os=8
    ppm_channels: int = 512
    bins: Sequence[int] = (1, 2, 3, 6)
    aux_head: bool = False
    dtype: jnp.dtype = jnp.float32
    bn_cross_replica_axis: str | None = None
    bn_fp32_stats: bool = True  # False: BN stats in compute dtype (see make_norm)
    remat: bool = False
    remat_policy: str | None = None  # jax.checkpoint_policies name (see ResNet)

    @nn.compact
    def __call__(self, x, train: bool = False):
        size = x.shape[1:3]
        feats = ResNet(
            depth=self.backbone_depth,
            output_stride=self.output_stride,
            dtype=self.dtype,
            bn_cross_replica_axis=self.bn_cross_replica_axis,
            bn_fp32_stats=self.bn_fp32_stats,
            remat=self.remat,
            remat_policy=self.remat_policy,
            name="backbone",
        )(x, train=train)
        norm = make_norm(train, self.dtype, self.bn_cross_replica_axis,
                 fp32_stats=self.bn_fp32_stats)
        y = PyramidPooling(channels=self.ppm_channels, bins=self.bins,
                           norm=norm, dtype=self.dtype,
                           name="ppm")(feats["c4"], train=train)
        y = nn.Conv(self.nclass, (1, 1), dtype=self.dtype,
                    name="classifier")(y)
        outs = [_resize_bilinear(y, size)]
        if self.aux_head:
            aux = FCNHead(nclass=self.nclass, norm=norm, dtype=self.dtype,
                          name="aux")(feats["c3"], train=train)
            outs.append(_resize_bilinear(aux, size))
        return tuple(outs)
