"""Model zoo: dilated ResNet backbones, DANet and DeepLabV3 heads.

``build_model`` is the single factory the trainer and configs use — the
framework equivalent of the reference's hardwired ``DANet(1, 'resnet101')``
construction (reference train_pascal.py:86) plus its commented DeepLab
alternative (train_pascal.py:85).

Contract: every model's ``__call__(x_nhwc, train)`` returns a *tuple* of
input-resolution logit maps, primary prediction first, so the multi-output
loss and eval code are model-agnostic.
"""

from __future__ import annotations

import jax.numpy as jnp

from .ccnet import CCNet, CrissCrossAttention, RCCAHead
from .danet import DANet, DANetHead
from .deeplab import ASPP, DeepLabV3, FCN, FCNHead
from .encnet import EncNet, EncNetHead, Encoding
from .pspnet import PSPNet, PyramidPooling
from .resnet import ResNet, resnet50, resnet101

_BACKBONE_DEPTH = {"resnet18": 18, "resnet34": 34, "resnet50": 50,
                   "resnet101": 101, "resnet152": 152}


def build_model(
    name: str = "danet",
    nclass: int = 1,
    backbone: str = "resnet101",
    output_stride: int | None = None,
    dtype: str | jnp.dtype = jnp.float32,
    bn_cross_replica_axis: str | None = None,
    bn_fp32_stats: bool = True,
    **kw,
):
    """Construct a segmentation model by name.

    ``dtype`` may be a string ('float32' / 'bfloat16') for config-file use.
    """
    if isinstance(dtype, str):
        dtype = jnp.dtype(dtype)
    if isinstance(kw.get("pam_score_dtype"), str):
        kw["pam_score_dtype"] = jnp.dtype(kw["pam_score_dtype"])
    depth = _BACKBONE_DEPTH[backbone]
    if name == "danet":
        # model.attention_impl — ONE knob for both attention branches:
        # 'auto' (default: the fused Pallas kernels for bf16 compute on
        # TPU — the mixed-precision hot path — XLA einsum otherwise; the
        # module resolves backend+dtype at trace time), 'xla' (einsum
        # everywhere, reference parity), 'flash' (force Pallas).
        # model.pam_impl, when set, overrides the position branch (its
        # extra forms — ring, blocked — stay reachable).
        attention_impl = kw.pop("attention_impl", "auto") or "auto"
        branch = {"auto": "auto", "xla": "einsum",
                  "flash": "flash"}.get(attention_impl)
        if branch is None:
            raise ValueError(
                f"unknown attention_impl: {attention_impl!r} "
                "(auto | xla | flash)")
        kw["pam_impl"] = kw.pop("pam_impl", "") or branch
        kw.setdefault("cam_impl", branch)
    else:
        # PAM/MoE options are DANet-only.  One config schema drives every
        # model family, so default values are silently dropped — but a
        # non-default setting on another model is a misconfiguration, not
        # something to train past.
        danet_only = {"pam_block_size": (None,),
                      # both the inherit sentinel and the legacy spelled-
                      # out default (pre-attention_impl configs on disk)
                      "pam_impl": ("", "einsum"),
                      "attention_impl": ("auto",),
                      "cam_impl": ("einsum",),
                      "pam_sp_mesh": (None,), "pam_sp_axis": ("model",),
                      "pam_score_dtype": (None,),
                      "moe_experts": (0,), "moe_hidden": (None,),
                      "moe_k": (1,), "moe_capacity_factor": (1.25,),
                      "guidance_inject": ("stem",)}
        for k, defaults in danet_only.items():
            if k in kw and kw.pop(k) not in defaults:
                raise ValueError(
                    f"{k} is DANet-only; model {name!r} does not support it")
    if name != "encnet" and kw.pop("encnet_codes", 32) != 32:
        raise ValueError(
            f"encnet_codes is EncNet-only; model {name!r} does not "
            "support it")
    if name != "ccnet" and kw.pop("ccnet_recurrence", 2) != 2:
        raise ValueError(
            f"ccnet_recurrence is CCNet-only; model {name!r} does not "
            "support it")
    if name == "danet":
        if kw.pop("aux_head", False):
            raise ValueError("aux_head is a DeepLabV3/FCN/PSPNet option; DANet's "
                             "three heads already provide multi-output "
                             "supervision")
        return DANet(
            nclass=nclass,
            backbone_depth=depth,
            output_stride=output_stride or 8,
            dtype=dtype,
            bn_cross_replica_axis=bn_cross_replica_axis,
            bn_fp32_stats=bn_fp32_stats,
            **kw,
        )
    if name in ("deeplabv3", "deeplabv3plus"):
        return DeepLabV3(
            nclass=nclass,
            backbone_depth=depth,
            output_stride=output_stride or 16,
            decoder=(name == "deeplabv3plus"),
            dtype=dtype,
            bn_cross_replica_axis=bn_cross_replica_axis,
            bn_fp32_stats=bn_fp32_stats,
            **kw,
        )
    if name == "fcn":
        return FCN(
            nclass=nclass,
            backbone_depth=depth,
            output_stride=output_stride or 8,
            dtype=dtype,
            bn_cross_replica_axis=bn_cross_replica_axis,
            bn_fp32_stats=bn_fp32_stats,
            **kw,
        )
    if name == "pspnet":
        return PSPNet(
            nclass=nclass,
            backbone_depth=depth,
            output_stride=output_stride or 8,
            dtype=dtype,
            bn_cross_replica_axis=bn_cross_replica_axis,
            bn_fp32_stats=bn_fp32_stats,
            **kw,
        )
    if name == "ccnet":
        kw["recurrence"] = kw.pop("ccnet_recurrence", 2)
        if kw["recurrence"] < 1:
            raise ValueError(
                f"ccnet_recurrence must be >= 1 (got {kw['recurrence']}): "
                "R=0 would skip the criss-cross module entirely, creating "
                "no attention params — a CCNet in name only")
        return CCNet(
            nclass=nclass,
            backbone_depth=depth,
            output_stride=output_stride or 8,
            dtype=dtype,
            bn_cross_replica_axis=bn_cross_replica_axis,
            bn_fp32_stats=bn_fp32_stats,
            **kw,
        )
    if name == "encnet":
        kw["n_codes"] = kw.pop("encnet_codes", 32)
        return EncNet(
            nclass=nclass,
            backbone_depth=depth,
            output_stride=output_stride or 8,
            dtype=dtype,
            bn_cross_replica_axis=bn_cross_replica_axis,
            bn_fp32_stats=bn_fp32_stats,
            **kw,
        )
    raise ValueError(
        f"unknown model: {name!r} (danet | deeplabv3 | deeplabv3plus | fcn "
        "| pspnet | encnet | ccnet)")


__all__ = [
    "ASPP",
    "CCNet",
    "CrissCrossAttention",
    "DANet",
    "DANetHead",
    "DeepLabV3",
    "EncNet",
    "EncNetHead",
    "Encoding",
    "RCCAHead",
    "FCN",
    "FCNHead",
    "PSPNet",
    "PyramidPooling",
    "ResNet",
    "build_model",
    "resnet50",
    "resnet101",
]
