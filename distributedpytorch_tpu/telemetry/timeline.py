"""Timeline merger: one causally-ordered story from a run's event logs.

A supervised run leaves N process generations (``run_<N>`` dirs plus the
supervisor's own process), each with per-(host, pid) event files under
``events/`` (:mod:`telemetry.events`).  This module stitches them into a
single ordered timeline and detects typed **episodes** — the recurring
incident shapes the doctor reports on:

* ``divergence_rollback``  — chaos/NaN strike -> sentinel rollback ->
  replay (recovery = the measured restore seconds)
* ``stall_ladder``         — governor arms an actuation -> stall drains
  -> hysteresis disarm (recovery = the arm->disarm span)
* ``preempt_resume``       — preemption signal / supervisor ``preempted``
  -> next generation spawned and fitting (recovery = the downtime span)
* ``crash_restart``        — supervisor ``crash`` -> next spawn
* ``topology_replan``      — supervisor ``topology_changed`` -> restore
  through the plan crossing in the next generation
* ``canary``               — swap admitted -> promoted / rolled back
* ``flywheel_cycle``       — one flywheel poll's verdict (committed /
  promoted / rolled_back / held)

Clock reconciliation: every event carries BOTH ``ts_wall`` and
``ts_mono``.  Within one process file, ``ts_mono`` is the truth — an
NTP step can never reorder a process against itself.  Across files, a
per-file offset (median of ``ts_wall - ts_mono`` over the file) maps
monotonic stamps onto one wall axis, so the merged order preserves each
process's internal order exactly and aligns processes by their median
wall clock — bounded host skew shifts a whole process, never shuffles
its cause and effect.  The generation chain (supervisor ledger +
``COMMITTED.json``) is the cross-check: process generations are serial
by construction.

Stdlib only (json/os/glob/statistics): the doctor must run on a dead
run dir from any machine, jax-free.
"""

from __future__ import annotations

import glob
import json
import os
import statistics

from .events import read_events_file, run_generation

#: governor actions that open a stall episode when applied (data/governor
#: ladder rungs that actuate; ``recommend``/``shortfall`` only advise)
_STALL_ARM = ("raise_prefetch", "flip_device_path", "arm_echo",
              "raise_echo")

#: episode types, closed set (doc + doctor rendering order)
EPISODE_TYPES = ("divergence_rollback", "stall_ladder", "preempt_resume",
                 "crash_restart", "topology_replan", "canary",
                 "flywheel_cycle", "replica_kill")


def _read_jsonl(path: str) -> list[dict]:
    out: list[dict] = []
    try:
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        pass
    return out


def _read_json(path: str) -> dict | None:
    try:
        with open(path) as f:
            rec = json.load(f)
        return rec if isinstance(rec, dict) else None
    except (OSError, ValueError):
        return None


def discover_event_files(path: str) -> list[str]:
    """Every event file under ``path``: its own ``events/`` plus every
    ``run_<N>/events/`` (a work_dir spanning generations, or one run)."""
    files = sorted(glob.glob(os.path.join(path, "events", "*.jsonl")))
    for run in sorted(glob.glob(os.path.join(path, "run_*"))):
        if run_generation(run) is not None:
            files.extend(sorted(glob.glob(
                os.path.join(run, "events", "*.jsonl"))))
    return files


def merge_events(files: list[str]) -> list[dict]:
    """Read, reconcile and merge event files into one ordered list.

    Each event gains ``t`` (reconciled wall time) and ``seq`` (its index
    in the merged order).  Per-file order is the file's append order
    (process-monotonic); the merge key is ``(t, file, line)`` so equal
    stamps stay deterministic."""
    streams: list[list[dict]] = []
    for path in files:
        evs = read_events_file(path)
        if not evs:
            continue
        # per-file monotonic->wall offset: the median survives a wall
        # step mid-run (half the samples would have to move to drag it)
        offset = statistics.median(
            e["ts_wall"] - e["ts_mono"] for e in evs)
        for i, e in enumerate(evs):
            e["t"] = e["ts_mono"] + offset
            e["_file"] = os.path.basename(path)
            e["_line"] = i
        streams.append(evs)
    merged = sorted((e for s in streams for e in s),
                    key=lambda e: (e["t"], e["_file"], e["_line"]))
    for seq, e in enumerate(merged):
        e["seq"] = seq
    return merged


def _close(ep: dict, ev: dict, recovery_s: float | None = None) -> None:
    ep["end"] = ev["t"]
    ep["events"].append(ev["seq"])
    ep["resolved"] = True
    ep["duration_s"] = round(ev["t"] - ep["start"], 3)
    if recovery_s is not None:
        ep["recovery_s"] = round(float(recovery_s), 3)
    elif ep.get("recovery_s") is None:
        ep["recovery_s"] = ep["duration_s"]


def _open(etype: str, ev: dict, **detail) -> dict:
    return {"type": etype, "start": ev["t"], "end": None,
            "duration_s": None, "recovery_s": None, "resolved": False,
            "generation": ev.get("generation"),
            "events": [ev["seq"]], "detail": detail}


def detect_episodes(events: list[dict]) -> tuple[list[dict], list[dict]]:
    """Typed episodes over a merged timeline; returns
    ``(episodes, orphans)`` where orphans are the opening events whose
    episode never closed (plus closers that matched nothing)."""
    episodes: list[dict] = []
    orphans: list[dict] = []

    # --- divergence -> rollback -> replay (sentinel) -------------------
    last_nan: dict | None = None
    open_rb: dict | None = None
    for ev in events:
        src, kind = ev["source"], ev["kind"]
        if src == "chaos" and kind == "nan":
            last_nan = ev
        elif src == "sentinel" and kind == "rollback":
            ep = _open("divergence_rollback", ev,
                       reason=ev["payload"].get("reason"),
                       rollback_to_step=ev["payload"].get(
                           "rollback_to_step"))
            if last_nan is not None and last_nan["t"] <= ev["t"]:
                ep["start"] = last_nan["t"]
                ep["events"].insert(0, last_nan["seq"])
                ep["detail"]["injected"] = True
                last_nan = None
            ep["recovery_s"] = ev["payload"].get("restore_seconds")
            episodes.append(ep)
            open_rb = ep
        elif src == "sentinel" and kind == "replay":
            if open_rb is not None and not open_rb["resolved"]:
                _close(open_rb, ev,
                       recovery_s=open_rb.get("recovery_s"))
                open_rb = None
            else:
                orphans.append(ev)

    # --- governor stall ladder ----------------------------------------
    open_stall: dict | None = None
    for ev in events:
        if ev["source"] != "governor":
            continue
        applied = bool(ev["payload"].get("applied"))
        if ev["kind"] in _STALL_ARM and applied:
            if open_stall is None:
                open_stall = _open("stall_ladder", ev,
                                   stall=ev["payload"].get("stall"),
                                   target=ev["payload"].get("target"))
                episodes.append(open_stall)
            else:
                open_stall["events"].append(ev["seq"])
        elif ev["kind"] == "disarm_echo" and applied:
            if open_stall is not None:
                _close(open_stall, ev)
                open_stall = None
            else:
                orphans.append(ev)

    # --- supervisor chains: preempt / crash / topology -----------------
    # a preemption signal inside generation g and the supervisor's own
    # classification of g's exit open the same episode — keep one
    open_chain: dict | None = None
    for ev in events:
        src, kind = ev["source"], ev["kind"]
        if src == "preemption" and kind == "preempt":
            if open_chain is None:
                open_chain = _open("preempt_resume", ev)
                episodes.append(open_chain)
        elif src == "supervisor" and kind in ("preempted", "crash",
                                              "topology_changed"):
            etype = {"preempted": "preempt_resume",
                     "crash": "crash_restart",
                     "topology_changed": "topology_replan"}[kind]
            if open_chain is not None and not open_chain["resolved"]:
                # reclassify: the supervisor's verdict on the same death
                # outranks the in-process signal sighting
                open_chain["type"] = etype
                open_chain["events"].append(ev["seq"])
                open_chain["detail"].update(ev["payload"])
            else:
                open_chain = _open(etype, ev, **ev["payload"])
                episodes.append(open_chain)
        elif src == "supervisor" and kind == "restart":
            if open_chain is not None and not open_chain["resolved"]:
                open_chain["events"].append(ev["seq"])
                # the supervisor's measured downtime is the episode's
                # recovery (the same number chaos_recovery_seconds
                # observes) — the episode SPAN additionally includes the
                # dying child's graceful drain, which is not downtime
                d = ev["payload"].get("downtime_s")
                if d is not None:
                    open_chain["recovery_s"] = round(float(d), 3)
        elif src == "supervisor" and kind == "spawn":
            if open_chain is not None and not open_chain["resolved"]:
                # downtime half: death classified -> next child spawned
                _close(open_chain, ev, recovery_s=None)
        elif src == "trainer" and kind == "fit_start":
            if (open_chain is not None and open_chain["resolved"]
                    and ev["payload"].get("resumed")):
                # extend through the resume: the episode's full recovery
                # is death -> restored-and-fitting again
                open_chain["events"].append(ev["seq"])
                open_chain["end"] = ev["t"]
                open_chain["duration_s"] = round(
                    ev["t"] - open_chain["start"], 3)
                if ev["payload"].get("plan_crossing"):
                    open_chain["detail"]["plan_crossing"] = True
                open_chain = None
        elif src == "checkpoint" and kind == "topology_crossing":
            if (open_chain is not None
                    and open_chain["type"] == "topology_replan"):
                open_chain["events"].append(ev["seq"])
                open_chain["detail"]["crossing"] = ev["payload"]
        elif src == "supervisor" and kind in ("clean_exit", "gave_up",
                                              "preempted_final"):
            open_chain = None

    # --- serve canary ---------------------------------------------------
    open_canary: dict[int, dict] = {}
    for ev in events:
        if ev["source"] != "serve":
            continue
        gen_id = ev["payload"].get("gen_id")
        if ev["kind"] == "swap_admit":
            ep = _open("canary", ev, gen_id=gen_id,
                       label=ev["payload"].get("label"))
            episodes.append(ep)
            open_canary[gen_id] = ep
        elif ev["kind"] in ("swap_promote", "swap_rollback"):
            ep = open_canary.pop(gen_id, None)
            if ep is None:
                orphans.append(ev)
                continue
            ep["detail"]["outcome"] = ("promoted"
                                       if ev["kind"] == "swap_promote"
                                       else "rolled_back")
            _close(ep, ev)

    # --- fleet replica kill -> respawn -> rejoin ------------------------
    # replica_down opens (the fleet front declared a replica dead);
    # the SAME replica's next replica_up closes (its respawn rejoined
    # the ring — slot ids are stable, so same-id IS same-slot).  The
    # front's failover events in between ride along as detail: how many
    # in-flight requests the death actually touched.  A replica_up with
    # no open episode is the normal boot lifecycle, not an orphan.
    open_replica: dict[str, dict] = {}
    for ev in events:
        if ev["source"] != "fleet":
            continue
        rid = ev["payload"].get("replica")
        if ev["kind"] == "replica_down":
            ep = _open("replica_kill", ev, replica=rid,
                       reason=ev["payload"].get("reason"), failovers=0)
            episodes.append(ep)
            open_replica[rid] = ep
        elif ev["kind"] == "failover":
            ep = open_replica.get(rid)
            if ep is not None and not ep["resolved"]:
                ep["events"].append(ev["seq"])
                ep["detail"]["failovers"] += 1
        elif ev["kind"] == "replica_up":
            ep = open_replica.pop(rid, None)
            if ep is not None and not ep["resolved"]:
                _close(ep, ev)
        elif ev["kind"] == "replica_removed":
            # a drained/retired slot never comes back: the down episode
            # (if any) resolves as a deliberate removal, not a recovery
            ep = open_replica.pop(rid, None)
            if ep is not None and not ep["resolved"]:
                ep["detail"]["removed"] = True
                _close(ep, ev)

    # --- flywheel cycles ------------------------------------------------
    for ev in events:
        if ev["source"] != "flywheel" or ev["kind"] == "idle":
            continue
        ep = _open("flywheel_cycle", ev, action=ev["kind"],
                   reason=ev["payload"].get("reason"))
        _close(ep, ev)
        episodes.append(ep)

    orphans.extend(ev for ep in episodes if not ep["resolved"]
                   for ev in [events[ep["events"][0]]])
    episodes.sort(key=lambda ep: ep["start"])
    return episodes, orphans


class Timeline:
    """The merged, episode-annotated record of one (possibly
    multi-generation) run."""

    def __init__(self, path: str):
        self.path = path
        self.files = discover_event_files(path)
        self.events = merge_events(self.files)
        self.episodes, self.orphans = detect_episodes(self.events)
        #: the supervisor's authoritative ledger (empty for unsupervised
        #: runs) — the generation chain's anchor
        self.supervisor = _read_jsonl(
            os.path.join(path, "supervisor.jsonl"))
        #: per-run committed steps (COMMITTED.json), the durable
        #: progress chain: {run_dir_basename: [steps...]}
        self.committed: dict[str, list[int]] = {}
        run_dirs = [path] + sorted(glob.glob(os.path.join(path, "run_*")))
        for rd in run_dirs:
            ledger = _read_json(
                os.path.join(rd, "checkpoints", "COMMITTED.json"))
            if ledger:
                self.committed[os.path.basename(rd) or rd] = \
                    [int(s) for s in ledger.get("latest", [])]

    @property
    def generations(self) -> list[int]:
        """Distinct process generations seen in the event stream."""
        return sorted({e["generation"] for e in self.events
                       if e.get("generation") is not None})

    def span_s(self) -> float:
        if not self.events:
            return 0.0
        return self.events[-1]["t"] - self.events[0]["t"]

    def by_source(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for e in self.events:
            out[e["source"]] = out.get(e["source"], 0) + 1
        return out

    def to_dict(self) -> dict:
        return {
            "path": self.path,
            "files": [os.path.relpath(f, self.path) for f in self.files],
            "events_total": len(self.events),
            "generations": self.generations,
            "span_s": round(self.span_s(), 3),
            "by_source": self.by_source(),
            "episodes": self.episodes,
            "orphans": [{k: e.get(k) for k in
                         ("seq", "source", "kind", "generation", "t")}
                        for e in self.orphans],
            "supervisor_events": len(self.supervisor),
            "committed": self.committed,
        }


def load_timeline(path: str) -> Timeline:
    """Stitch the timeline of ``path`` (a work_dir spanning run_<N>
    generations, or a single run dir)."""
    return Timeline(path)
