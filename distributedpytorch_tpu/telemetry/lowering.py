"""Shared XLA lowering/compile cache: one lower per program per process.

Two independent consumers need the lowered form of the hot compiled
programs — the MFU estimator (:func:`goodput.xla_step_cost` wants
``cost_analysis`` FLOPs of the exact step) and the IR auditor
(:mod:`analysis.ir` wants the ClosedJaxpr, the compiled HLO and
``memory_analysis``).  Each ``fn.lower(*args)`` is a full re-trace and —
absent the persistent compile cache — a re-compile, so letting every
consumer lower privately multiplies the single most expensive host
operation in the process.  This module is the one place a program gets
lowered: entries are keyed by ``(fn identity, abstract arg signature)``,
so a caller holding concrete arrays and a caller holding
``ShapeDtypeStruct`` templates of the same program share one entry.

The cache holds strong references to ``fn`` (which also keeps the ``id``
key stable) and to the traced/lowered/compiled stages; programs audited
or costed are the long-lived steps of the process, so this is bounded by
the number of distinct compiled programs — the same bound jax's own jit
cache already lives under.
"""

from __future__ import annotations

import threading


class LoweredProgram:
    """One program's trace → lower → compile pipeline, each stage computed
    once and memoized.  ``traced`` is None on jax versions without the
    AOT ``fn.trace`` API (everything downstream still works; only
    jaxpr-level auditing degrades)."""

    __slots__ = ("fn", "traced", "lowered", "_compiled", "_cost")

    def __init__(self, fn, traced, lowered):
        self.fn = fn
        self.traced = traced
        self.lowered = lowered
        self._compiled = None
        self._cost = None

    @property
    def compiled(self):
        if self._compiled is None:
            self._compiled = self.lowered.compile()
        return self._compiled

    def cost(self) -> dict:
        """XLA's cost model: ``{"flops", "bytes"}``, None when the backend
        has no cost model (same contract as the old goodput helper)."""
        if self._cost is None:
            try:
                cost = self.compiled.cost_analysis()
                if isinstance(cost, (list, tuple)):  # older jax: [dict]
                    cost = cost[0]
                self._cost = {
                    "flops": float(cost["flops"]),
                    "bytes": float(cost.get("bytes accessed", 0.0)) or None,
                }
            except Exception:
                self._cost = {"flops": None, "bytes": None}
        return self._cost


_LOCK = threading.Lock()
_CACHE: dict = {}


def _leaf_signature(leaf) -> tuple:
    """Abstract signature of one arg leaf: concrete jax/numpy arrays and
    ShapeDtypeStructs of the same shape/dtype hash identically, so the
    trainer's concrete-state lowering and the auditor's struct-only
    lowering share an entry.  ``weak_type`` is part of the signature —
    jax's own jit cache distinguishes it (promotion, and therefore the
    traced program, differs), so colliding the two would hand one
    caller the other's jaxpr."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        return (tuple(shape), str(dtype),
                bool(getattr(leaf, "weak_type", False)))
    return ("py", type(leaf).__name__, repr(leaf)[:64])


def program_key(fn, args: tuple) -> tuple:
    import jax

    leaves, treedef = jax.tree.flatten(args)
    return (id(fn), str(treedef), tuple(_leaf_signature(x) for x in leaves))


def lower_cached(fn, *args) -> LoweredProgram:
    """The (memoized) lowered form of ``fn`` at ``args`` (concrete arrays
    or ShapeDtypeStructs).  Raises whatever trace/lower raises — callers
    that must never fail (the MFU estimator) wrap it."""
    key = program_key(fn, args)
    with _LOCK:
        prog = _CACHE.get(key)
    if prog is not None:
        return prog
    if hasattr(fn, "trace"):  # AOT API: keeps the ClosedJaxpr + args_info
        traced = fn.trace(*args)
        lowered = traced.lower()
    else:
        traced = None
        lowered = fn.lower(*args)
    prog = LoweredProgram(fn, traced, lowered)
    with _LOCK:
        # a racing thread may have lowered the same program; keep the
        # first entry so every consumer shares one executable
        prog = _CACHE.setdefault(key, prog)
    return prog


def cache_info() -> dict:
    with _LOCK:
        return {"entries": len(_CACHE)}


def clear_cache() -> None:
    """Tests only: drop every cached stage (frees the executables)."""
    with _LOCK:
        _CACHE.clear()
