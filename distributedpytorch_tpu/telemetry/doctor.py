"""``dptpu-doctor``: read a run dir, tell the operator what happened.

The diagnosis layer over the flight recorder: load the stitched
timeline (:mod:`telemetry.timeline`), break down where the wall-clock
went, list every episode with its recovery time, and raise **findings**
— anomalies with the exact config knob or CLI remedy, in the feed
governor's recommendation idiom (a finding that does not name its fix
is a shrug, not a diagnosis).  Optionally folds in a live replica's
``/metrics`` text (``--metrics URL-or-file``) so serve-side counters
(swap outcomes, dropped telemetry deltas) join the verdict.

Findings carry a severity: ``info`` (observation), ``warning``
(degraded but recovered), ``critical`` (unresolved — the run needs a
human or a config change).  The process exits non-zero when any
critical finding stands, so the doctor can gate CI and chaos scenarios;
``--json`` emits the full report for machines.

Stdlib only, importable pre-jax: a dead run dir must be diagnosable
from any machine, no accelerator stack required.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

from .timeline import Timeline, load_timeline

#: finding severities, escalation order
SEVERITIES = ("info", "warning", "critical")

#: default thresholds the anomaly detectors judge against (each finding
#: names the threshold it tripped so the verdict is reproducible)
THRESHOLDS = {
    # wall-clock between events with nothing booked against it
    "unbooked_gap_s": 120.0,
    # repeated canary rollbacks without a promote in between
    "canary_rollbacks": 2,
    # quarantined batches across the run
    "quarantine_batches": 8,
    # sentinel rollbacks across the run
    "rollbacks": 3,
}


def _finding(severity: str, code: str, message: str, remedy: str,
             **detail) -> dict:
    assert severity in SEVERITIES
    return {"severity": severity, "code": code, "message": message,
            "remedy": remedy, "detail": detail}


def _read_jsonl(path: str) -> list[dict]:
    out = []
    try:
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        pass
    return out


def _fit_summaries(path: str) -> list[tuple[str, dict]]:
    out = []
    for rd in [path] + sorted(glob.glob(os.path.join(path, "run_*"))):
        p = os.path.join(rd, "fit_summary.json")
        try:
            with open(p) as f:
                out.append((os.path.basename(rd) or rd, json.load(f)))
        except (OSError, ValueError):
            continue
    return out


def parse_metrics_text(text: str) -> dict[str, float]:
    """Prometheus 0.0.4 text -> ``{'name{labels}': value}``; quantile
    and comment lines keep their exact exposition key so callers can
    select with plain substring checks."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            key, val = line.rsplit(" ", 1)
            out[key] = float(val)
        except ValueError:
            continue
    return out


def fetch_metrics(source: str) -> dict[str, float]:
    """``--metrics``: a file path or an ``http(s)://`` URL (a live
    replica's ``GET /metrics``)."""
    if source.startswith(("http://", "https://")):
        import urllib.request

        with urllib.request.urlopen(source, timeout=10) as resp:
            return parse_metrics_text(resp.read().decode("utf-8"))
    with open(source) as f:
        return parse_metrics_text(f.read())


def _metric_total(metrics: dict[str, float], name: str) -> float:
    return sum(v for k, v in metrics.items()
               if k == name or k.startswith(name + "{"))


# ------------------------------------------------------------- analysis

def goodput_breakdown(tl: Timeline) -> dict:
    """Aggregate the per-generation goodput blocks off the ``fit_end``
    anchors: summed buckets, the overall productive fraction, and the
    top wall-clock sinks (largest non-step buckets first)."""
    buckets: dict[str, float] = {}
    total = 0.0
    fits = 0
    for ev in tl.events:
        if ev["source"] != "trainer" or ev["kind"] != "fit_end":
            continue
        gp = ev["payload"].get("goodput") or {}
        if not gp.get("buckets"):
            continue
        fits += 1
        total += gp.get("total_s") or 0.0
        for b, v in gp["buckets"].items():
            if v is not None:
                buckets[b] = buckets.get(b, 0.0) + float(v)
    sinks = sorted(((b, s) for b, s in buckets.items() if b != "step"),
                   key=lambda kv: -kv[1])
    return {
        "fits": fits,
        "total_s": round(total, 3),
        "buckets": {b: round(s, 3) for b, s in buckets.items()},
        "productive_frac": (round(buckets.get("step", 0.0) / total, 4)
                            if total > 0 else None),
        "top_sinks": [{"bucket": b, "seconds": round(s, 3)}
                      for b, s in sinks[:3]],
    }


def detect_findings(tl: Timeline, path: str,
                    metrics: dict[str, float] | None = None,
                    thresholds: dict | None = None) -> list[dict]:
    th = dict(THRESHOLDS)
    th.update(thresholds or {})
    findings: list[dict] = []

    if not tl.events:
        findings.append(_finding(
            "warning", "no_events",
            f"no flight-recorder events under {path}",
            "run with telemetry=true (config) so run_dir/events/ is "
            "written; pre-recorder runs can only be read via their "
            "per-subsystem ledgers"))
        return findings

    # --- unresolved episodes (the critical class) ----------------------
    for ep in tl.episodes:
        if ep["resolved"]:
            continue
        code = f"unresolved_{ep['type']}"
        remedy = {
            "divergence_rollback":
                "rollback never replayed: check sentinel.max_rollbacks "
                "(budget may be exhausted) and quarantine.jsonl for the "
                "poisoned window",
            "stall_ladder":
                "input stall armed and never drained: raise "
                "data.max_echo, enable data.device_augment, or pack the "
                "source (dptpu-pack) per the governor's "
                "pack_recommendation",
            "preempt_resume":
                "preemption without a resumed generation: run under "
                "dptpu-supervise (restart_on_preempt) or resume=auto "
                "the next run manually",
            "crash_restart":
                "crash without a restart: check supervisor.jsonl for "
                "gave_up and raise --max-restarts if the budget ended "
                "the storm",
            "topology_replan":
                "topology changed but no replanned generation fit: "
                "launch with parallel.strategy=auto so the restart "
                "re-resolves its plan",
            "canary":
                "canary admitted but never decided: call promote() or "
                "rollback(), or lower promote_after so observation "
                "traffic decides it",
            "flywheel_cycle":
                "flywheel cycle left open: check flywheel.jsonl",
            "replica_kill":
                "replica declared dead and never rejoined the ring: "
                "check its <slot>.log in the fleet workdir, and the "
                "--max-restarts budget (a spent budget stops the "
                "respawns; /fleet/plan still counts the lost capacity)",
        }[ep["type"]]
        findings.append(_finding(
            "critical", code,
            f"{ep['type']} episode opened at t={ep['start']:.3f} "
            f"(generation {ep['generation']}) and never resolved",
            remedy, episode=ep))

    # --- stall above target at end of run ------------------------------
    last_gov = None
    for ev in tl.events:
        if ev["source"] == "governor":
            last_gov = ev
    if last_gov is not None:
        stall = last_gov["payload"].get("stall")
        target = last_gov["payload"].get("target")
        if (stall is not None and target is not None and stall > target
                and last_gov["kind"] != "disarm_echo"):
            findings.append(_finding(
                "warning", "stall_above_target",
                f"final governor reading has input_wait fraction "
                f"{stall:.4f} above target {target} "
                f"(last action: {last_gov['kind']})",
                "the run ended feed-bound: pack the source (dptpu-pack), "
                "raise data.max_echo, or enable data.device_augment / "
                "data.device_guidance",
                stall=stall, target=target, action=last_gov["kind"]))

    # --- rollback budget burn ------------------------------------------
    rollbacks = [e for e in tl.events
                 if e["source"] == "sentinel" and e["kind"] == "rollback"]
    if len(rollbacks) >= th["rollbacks"]:
        findings.append(_finding(
            "warning", "rollback_budget_burn",
            f"{len(rollbacks)} sentinel rollbacks (threshold "
            f"{th['rollbacks']}) — the run is burning its rollback "
            "budget",
            "inspect quarantine.jsonl for the poisoned inputs; if the "
            "divergence is numeric (not data), lower optim.lr or raise "
            "sentinel.diverged_factor",
            rollbacks=len(rollbacks)))

    # --- quarantine growth ---------------------------------------------
    quarantined = 0
    for rd in [path] + sorted(glob.glob(os.path.join(path, "run_*"))):
        for rec in _read_jsonl(os.path.join(rd, "quarantine.jsonl")):
            quarantined += len(rec.get("batch_indices") or [])
    if quarantined >= th["quarantine_batches"]:
        findings.append(_finding(
            "warning", "quarantine_growth",
            f"{quarantined} batches quarantined across the run "
            f"(threshold {th['quarantine_batches']})",
            "the skip set is eating the dataset: fix the poisoned "
            "records (dptpu-pack --verify names torn ones) or clear "
            "data.pack_quarantine after repair",
            quarantined_batches=quarantined))

    # --- repeated canary rollbacks -------------------------------------
    rb_run = 0
    for ep in tl.episodes:
        if ep["type"] != "canary" or not ep["resolved"]:
            continue
        if ep["detail"].get("outcome") == "rolled_back":
            rb_run += 1
        else:
            rb_run = 0
    if rb_run >= th["canary_rollbacks"]:
        findings.append(_finding(
            "warning", "repeated_canary_rollbacks",
            f"{rb_run} consecutive canary rollbacks without a promote",
            "every new generation is failing its canary: raise the "
            "flywheel's --min-improvement (weed out marginal fits) and "
            "check the fit sentinel/quarantine evidence before the next "
            "swap",
            consecutive_rollbacks=rb_run))

    # --- unexplained generation gaps -----------------------------------
    # between one generation's last event and the next generation's
    # first, time should be booked by a supervisor classify->spawn pair;
    # a long silent gap is unbooked wall-clock
    gen_events: dict[int, list[dict]] = {}
    for ev in tl.events:
        g = ev.get("generation")
        if g is not None and ev["source"] != "supervisor":
            gen_events.setdefault(g, []).append(ev)
    gens = sorted(gen_events)
    for a, b in zip(gens, gens[1:]):
        t_end = gen_events[a][-1]["t"]
        t_start = gen_events[b][0]["t"]
        gap = t_start - t_end
        if gap < th["unbooked_gap_s"]:
            continue
        explained = any(
            e["source"] == "supervisor" and t_end <= e["t"] <= t_start
            for e in tl.events)
        if not explained:
            findings.append(_finding(
                "critical", "unexplained_generation_gap",
                f"{gap:.1f}s of unbooked wall-clock between generation "
                f"{a} and {b} with no supervisor event explaining it "
                f"(threshold {th['unbooked_gap_s']}s)",
                "the run restarted outside supervision: launch under "
                "dptpu-supervise so restarts are classified and booked",
                gap_s=round(gap, 1), from_generation=a, to_generation=b))

    # --- last generation never finished --------------------------------
    starts = [e for e in tl.events
              if e["source"] == "trainer" and e["kind"] == "fit_start"]
    ends = [e for e in tl.events
            if e["source"] == "trainer" and e["kind"] == "fit_end"]
    if starts:
        last_gen = starts[-1].get("generation")
        ended = any(e.get("generation") == last_gen for e in ends)
        sup_closed = any(
            s.get("event") in ("clean_exit", "clean_exit_unverified")
            for s in tl.supervisor)
        if not ended and not sup_closed:
            findings.append(_finding(
                "critical", "run_incomplete",
                f"generation {last_gen} opened a fit and never closed "
                "it, and no supervisor clean_exit explains the end",
                "the last process died mid-fit: resume with resume=auto "
                "(the COMMITTED ledger names the restart step) or run "
                "under dptpu-supervise",
                generation=last_gen))

    # --- dropped telemetry deltas (live /metrics) ----------------------
    if metrics:
        dropped = _metric_total(metrics, "telemetry_dropped_deltas_total")
        if dropped > 0:
            findings.append(_finding(
                "warning", "dropped_telemetry_deltas",
                f"{int(dropped)} negative goodput deltas dropped "
                "(telemetry_dropped_deltas_total) — a clock reset or "
                "accountant reset raced the feed window",
                "benign once per fit start; a growing count means "
                "something resets the accountant mid-fit — check for "
                "concurrent fits sharing the process",
                dropped=dropped))
        swap_rb = _metric_total(
            metrics, "serve_swaps_total")
        if swap_rb:
            findings.append(_finding(
                "info", "serve_swaps_observed",
                f"{int(swap_rb)} swap decisions on the live replica",
                "no action needed; see the canary episodes for outcomes",
                swaps=swap_rb))

    order = {s: i for i, s in enumerate(SEVERITIES)}
    findings.sort(key=lambda f: -order[f["severity"]])
    return findings


def diagnose(path: str, metrics: dict[str, float] | None = None,
             thresholds: dict | None = None) -> dict:
    """The full report: timeline + goodput + episodes + findings +
    verdict.  ``verdict`` is the highest standing severity ('healthy'
    when no finding stands)."""
    tl = load_timeline(path)
    findings = detect_findings(tl, path, metrics=metrics,
                               thresholds=thresholds)
    worst = "healthy"
    for f in findings:
        if f["severity"] == "critical":
            worst = "critical"
            break
        if f["severity"] == "warning":
            worst = "warning"
    return {
        "path": path,
        "verdict": worst,
        "timeline": tl.to_dict(),
        "goodput": goodput_breakdown(tl),
        "fit_summaries": [name for name, _ in _fit_summaries(path)],
        "findings": findings,
    }


# ------------------------------------------------------------ rendering

def render(report: dict) -> str:
    lines: list[str] = []
    tl = report["timeline"]
    add = lines.append
    add(f"dptpu-doctor: {report['path']}")
    add(f"verdict: {report['verdict'].upper()}")
    add(f"events: {tl['events_total']} across "
        f"{len(tl['files'])} file(s), generations {tl['generations']}, "
        f"span {tl['span_s']}s")
    if tl["by_source"]:
        add("  by source: " + ", ".join(
            f"{s}={n}" for s, n in sorted(tl["by_source"].items())))
    gp = report["goodput"]
    if gp["fits"]:
        add(f"goodput: {gp['productive_frac']} productive over "
            f"{gp['total_s']}s ({gp['fits']} fit(s))")
        for sink in gp["top_sinks"]:
            add(f"  sink: {sink['bucket']:<12} {sink['seconds']}s")
    add(f"episodes: {len(tl['episodes'])}")
    for ep in tl["episodes"]:
        state = "resolved" if ep["resolved"] else "UNRESOLVED"
        rec = (f", recovery {ep['recovery_s']}s"
               if ep.get("recovery_s") is not None else "")
        add(f"  [{state}] {ep['type']} gen={ep['generation']}"
            f"{rec} ({len(ep['events'])} events)")
    if tl["orphans"]:
        add(f"orphan events: {len(tl['orphans'])}")
        for o in tl["orphans"]:
            add(f"  seq={o['seq']} {o['source']}/{o['kind']} "
                f"gen={o['generation']}")
    add(f"findings: {len(report['findings'])}")
    for f in report["findings"]:
        add(f"  [{f['severity'].upper()}] {f['code']}: {f['message']}")
        add(f"    remedy: {f['remedy']}")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="dptpu-doctor",
        description="diagnose a run dir from its flight-recorder "
                    "timeline")
    ap.add_argument("path", help="run dir or supervisor work dir")
    ap.add_argument("--metrics", default=None,
                    help="Prometheus text to fold in: a file path or a "
                         "live replica's /metrics URL")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine report instead of text")
    ap.add_argument("--threshold", action="append", default=[],
                    metavar="KEY=VALUE",
                    help=f"override an anomaly threshold "
                         f"(one of {sorted(THRESHOLDS)})")
    args = ap.parse_args(argv)
    thresholds = {}
    for kv in args.threshold:
        k, _, v = kv.partition("=")
        if k not in THRESHOLDS:
            ap.error(f"unknown threshold {k!r} "
                     f"(one of {sorted(THRESHOLDS)})")
        thresholds[k] = float(v)
    metrics = fetch_metrics(args.metrics) if args.metrics else None
    report = diagnose(args.path, metrics=metrics, thresholds=thresholds)
    if args.json:
        print(json.dumps(report, indent=2, allow_nan=False))
    else:
        print(render(report))
    # non-zero on critical findings: the CI / chaos gate
    return 1 if report["verdict"] == "critical" else 0


if __name__ == "__main__":
    sys.exit(main())
