"""Unified telemetry: registry, spans, goodput/MFU, traces, Prometheus.

One process-wide surface for "what is this process doing":

* :mod:`registry`   — thread-safe counters/gauges/histograms
  (:func:`get_registry` is the process singleton);
* :mod:`spans`      — nested host spans mirrored into XPlane device
  traces via ``jax.profiler.TraceAnnotation``;
* :mod:`goodput`    — wall-clock attribution ({step, compile,
  checkpoint, eval, input_wait, idle}) + MFU estimation with the
  device-kind peak-FLOPs table;
* :mod:`prometheus` — text exposition for ``GET /metrics``;
* :mod:`trace`      — on-demand bounded ``jax.profiler`` capture
  (SIGUSR2 / ``POST /debug/trace``) without restarting the process;
* :mod:`lowering`   — process-wide trace/lower/compile cache shared by
  the MFU estimator and the IR auditor (``analysis.ir``), so each hot
  program is lowered exactly once;
* :mod:`events`     — the flight recorder: one crash-safe, append-only
  run-event log (``run_dir/events/<host>.<pid>.jsonl``) every subsystem
  publishes into without changing its own ledger;
* :mod:`timeline`   — merges a run dir's event files across process
  generations and hosts into one causally-ordered timeline with typed
  episodes (divergence→rollback→replay, preempt→resume, …);
* :mod:`doctor`     — ``dptpu-doctor``: the diagnosis CLI over the
  timeline (goodput breakdown, episode recovery times, anomaly findings
  with the exact config-knob remedy).

Every future perf PR reports into this layer; the train loop, the
checkpoint manager, the evaluator and the serve front are already wired.
"""

from . import events, goodput, lowering, prometheus, registry, spans, timeline, trace
from .events import EventLog, events_block
from .timeline import Timeline, load_timeline
from .goodput import (
    BUCKETS,
    FeedWindow,
    GoodputAccountant,
    get_accountant,
    mfu_estimate,
    peak_flops_for,
)
from .lowering import LoweredProgram, lower_cached
from .prometheus import render_text
from .registry import MetricsRegistry, get_registry, is_enabled, set_enabled
from .spans import current_span, span
from .trace import TraceCapture

__all__ = [
    "BUCKETS", "EventLog", "FeedWindow", "GoodputAccountant",
    "LoweredProgram", "MetricsRegistry", "Timeline",
    "TraceCapture", "current_span", "events", "events_block",
    "get_accountant", "get_registry",
    "goodput", "is_enabled", "load_timeline", "lower_cached", "lowering",
    "mfu_estimate",
    "peak_flops_for", "prometheus", "registry", "render_text",
    "set_enabled", "span", "spans", "timeline", "trace",
]
