"""Unified telemetry: registry, spans, goodput/MFU, traces, Prometheus.

One process-wide surface for "what is this process doing":

* :mod:`registry`   — thread-safe counters/gauges/histograms
  (:func:`get_registry` is the process singleton);
* :mod:`spans`      — nested host spans mirrored into XPlane device
  traces via ``jax.profiler.TraceAnnotation``;
* :mod:`goodput`    — wall-clock attribution ({step, compile,
  checkpoint, eval, input_wait, idle}) + MFU estimation with the
  device-kind peak-FLOPs table;
* :mod:`prometheus` — text exposition for ``GET /metrics``;
* :mod:`trace`      — on-demand bounded ``jax.profiler`` capture
  (SIGUSR2 / ``POST /debug/trace``) without restarting the process;
* :mod:`lowering`   — process-wide trace/lower/compile cache shared by
  the MFU estimator and the IR auditor (``analysis.ir``), so each hot
  program is lowered exactly once.

Every future perf PR reports into this layer; the train loop, the
checkpoint manager, the evaluator and the serve front are already wired.
"""

from . import goodput, lowering, prometheus, registry, spans, trace
from .goodput import (
    BUCKETS,
    FeedWindow,
    GoodputAccountant,
    get_accountant,
    mfu_estimate,
    peak_flops_for,
)
from .lowering import LoweredProgram, lower_cached
from .prometheus import render_text
from .registry import MetricsRegistry, get_registry, is_enabled, set_enabled
from .spans import current_span, span
from .trace import TraceCapture

__all__ = [
    "BUCKETS", "FeedWindow", "GoodputAccountant", "LoweredProgram",
    "MetricsRegistry",
    "TraceCapture", "current_span", "get_accountant", "get_registry",
    "goodput", "is_enabled", "lower_cached", "lowering", "mfu_estimate",
    "peak_flops_for", "prometheus", "registry", "render_text",
    "set_enabled", "span", "spans", "trace",
]
