"""On-demand, bounded ``jax.profiler`` trace capture — no restart needed.

The existing profiling story required deciding BEFORE launch
(``profile_epoch`` config, ``utils.profiling.trace`` around a region);
the interesting step regression always shows up mid-run.
:class:`TraceCapture` arms a capture from the outside of a live process —
``SIGUSR2`` on the trainer, ``POST /debug/trace?steps=N`` on the serve
front — and the owning loop drives it with one cheap :meth:`tick` per
step/batch: the next tick after a request starts the trace, N ticks later
it stops, and the XPlane files land under the run dir
(``trace_on_demand/trace_NNN``) for tensorboard/xprof.

Safety properties, each deliberate:

* **Bounded.**  Steps are clamped to ``max_steps`` and a wall-clock
  ``max_seconds`` backstop closes a trace even if the step flow stalls
  (a serve instance that goes idle mid-capture must not profile
  forever — unbounded traces fill disks).
* **Signal-safe arming.**  :meth:`request` only assigns plain attributes
  (no locks): it is safe to call from a signal handler interrupting
  arbitrary code.  All real work happens in :meth:`tick` on the owning
  loop's thread.
* **One at a time.**  ``jax.profiler`` supports a single active trace
  per process; a request while one is active or armed is refused
  (returns None) rather than queued.
* **Never fatal.**  Profiler failures are counted
  (``trace_capture_failures_total``) and printed, never raised into the
  train loop.
"""

from __future__ import annotations

import os
import signal
import threading
import time

from .registry import MetricsRegistry, get_registry


class TraceCapture:
    """Armed-from-outside bounded device trace; driven by ``tick``.

    ``tick(n)`` means "n more steps are about to run": the owning loop
    calls it immediately before each dispatch (the trainer passes its
    steps-per-dispatch; the serve worker passes 1 per batch and 0 on
    idle polls so the time backstop still runs).
    """

    def __init__(self, log_dir: str, default_steps: int = 20,
                 max_steps: int = 200, max_seconds: float = 120.0,
                 registry: MetricsRegistry | None = None):
        self.log_dir = log_dir
        self.default_steps = default_steps
        self.max_steps = max_steps
        self.max_seconds = max_seconds
        self._registry = registry
        # armed-request slot: written by request() (possibly from a signal
        # handler), consumed by tick() on the owning thread.  The arm
        # itself is guarded by a NON-BLOCKING try-lock: concurrent HTTP
        # threads cannot both claim the slot, and a signal handler that
        # finds the lock held simply refuses (acquire(False) never blocks,
        # so it can never deadlock against interrupted code).
        self._arm_lock = threading.Lock()
        self._want = 0
        # active-capture state: owned exclusively by the tick()er's thread
        self._active = False
        self._remaining = 0
        self._started = 0.0
        self._dir = ""
        self._captures = 0

    # ------------------------------------------------------------- arming
    @property
    def active(self) -> bool:
        return self._active

    def request(self, steps: int | None = None) -> str | None:
        """Arm a capture of ``steps`` (clamped to [1, max_steps]); the
        next STEP tick starts it.  Returns the directory the trace will
        land in, or None when one is already armed/active (refused, not
        queued).  Safe to call from signal handlers and HTTP threads."""
        if not self._arm_lock.acquire(blocking=False):
            return None  # concurrent arm in flight — refuse, never block
        try:
            if self._active or self._want:
                return None
            n = self.default_steps if steps is None else int(steps)
            target = os.path.join(self.log_dir,
                                  f"trace_{self._captures:03d}")
            # write the target BEFORE arming: tick() may fire between the
            # two assignments and must already see where to write
            self._pending_dir = target
            self._want = max(1, min(self.max_steps, n))
            return target
        finally:
            self._arm_lock.release()

    def install_signal(self, signum: int | None = None):
        """Install a SIGUSR2 (default) handler that arms a default
        capture; returns an uninstall callable.  Off the main thread
        (where ``signal.signal`` raises) this degrades to a no-op —
        ``request()`` still works programmatically."""
        if signum is None:
            signum = getattr(signal, "SIGUSR2", None)
            if signum is None:  # platform without SIGUSR2
                return lambda: None
        try:
            prev = signal.signal(signum, lambda s, f: self.request())
        except ValueError:
            return lambda: None
        return lambda: signal.signal(signum, prev)

    # ------------------------------------------------------------- driving
    def tick(self, n: int = 1) -> None:
        """Advance by ``n`` imminent steps (0 = just service the time
        backstop).  Called from exactly one thread — the step loop."""
        if self._active:
            if self._remaining <= 0 or \
                    time.perf_counter() - self._started > self.max_seconds:
                self._stop()
            else:
                self._remaining -= n
        elif self._want and n > 0:
            # start only on a REAL step tick: an idle tick(0) opening the
            # trace would burn the wall-clock backstop on idle time and
            # could close a serve capture having traced zero batches
            steps = self._want
            self._want = 0
            self._start(steps)
            self._remaining = steps - n
        # else: idle — one attribute read, the per-step cost when unarmed

    def close(self) -> None:
        """Stop any in-flight capture (call at fit end / service stop)."""
        if self._active:
            self._stop()

    # ------------------------------------------------------------ internals
    def _reg(self) -> MetricsRegistry:
        return self._registry or get_registry()

    def _start(self, steps: int) -> None:
        import jax

        self._dir = getattr(self, "_pending_dir", None) or os.path.join(
            self.log_dir, f"trace_{self._captures:03d}")
        try:
            os.makedirs(self._dir, exist_ok=True)
            jax.profiler.start_trace(self._dir)
        except Exception as e:  # another trace active, or profiler error
            self._reg().counter("trace_capture_failures_total",
                                "on-demand trace captures that failed").inc()
            print(f"telemetry: trace capture failed to start: {e}",
                  flush=True)
            return
        self._active = True
        self._started = time.perf_counter()
        print(f"telemetry: capturing {steps}-step trace -> {self._dir}",
              flush=True)

    def _stop(self) -> None:
        import jax

        try:
            jax.profiler.stop_trace()
        except Exception as e:
            self._reg().counter("trace_capture_failures_total",
                                "on-demand trace captures that failed").inc()
            print(f"telemetry: trace capture failed to stop: {e}",
                  flush=True)
        else:
            self._reg().counter("trace_captures_total",
                                "on-demand trace captures completed").inc()
            print(f"telemetry: trace written -> {self._dir}", flush=True)
        self._active = False
        self._captures += 1


#: serve-side convenience: arm via HTTP thread, driven by the worker loop
def query_steps(query: str, default: int | None = None) -> int | None:
    """Parse ``steps=N`` out of a raw query string (bad values -> default)."""
    from urllib.parse import parse_qs

    try:
        vals = parse_qs(query).get("steps")
        return int(vals[0]) if vals else default
    except (ValueError, TypeError):
        return default
