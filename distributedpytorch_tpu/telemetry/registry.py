"""Process-wide metrics registry: counters, gauges, histograms.

Before this package, every subsystem kept private observability state —
``train/logging.py`` writers, ``utils/profiling.StepTimer`` lists,
``serve/metrics.ServeMetrics`` counters, the CompileWatchdog's counts —
with no shared surface, so "what is this process doing" had no single
answer.  The registry is that surface: one thread-safe, process-wide
name -> metric table that the Prometheus renderer (prometheus.py), the
goodput accountant (goodput.py) and the span recorder (spans.py) all
write into, and that ``GET /metrics`` on the serve front reads out.

Three primitive kinds, deliberately small:

* :class:`Counter`  — monotonic float (requests served, signals seen);
* :class:`Gauge`    — last-write-wins float (queue depth, goodput ratio);
* :class:`Histogram`— bounded reservoir of recent samples with
  nearest-rank percentiles (:func:`utils.profiling.percentile` — the
  same rule StepTimer and the serve latency tail already use) plus
  monotonic ``count``/``sum`` so rates stay derivable after the
  reservoir wraps.

Metrics support Prometheus-style labels: ``registry.counter("x_total",
labels={"bucket": "8"})`` returns the child for that label set; children
of one name form a family that renders together.  Everything is
host-side Python — no jax, no device work — so instrumentation can sit
at step-loop boundaries without tripping jaxlint's host-sync rules.
"""

from __future__ import annotations

import collections
import re
import threading

from ..utils.profiling import percentile

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: empty-labelset key (the unlabeled child of a family)
_NO_LABELS: tuple = ()


def _label_key(labels: dict | None) -> tuple:
    if not labels:
        return _NO_LABELS
    for k in labels:
        if not _LABEL_RE.match(k):
            raise ValueError(f"bad label name {k!r}")
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotonic counter.  ``inc`` only; decrements are a bug by type."""

    __slots__ = ("labels", "_lock", "_value")

    def __init__(self, labels: tuple = _NO_LABELS):
        self.labels = labels
        self._lock = threading.Lock()
        self._value = 0.0  # jaxrace: guarded-by=self._lock

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter increment must be >= 0, got {n}")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Last-write-wins value; ``inc``/``dec`` for up-down accounting."""

    __slots__ = ("labels", "_lock", "_value")

    def __init__(self, labels: tuple = _NO_LABELS):
        self.labels = labels
        self._lock = threading.Lock()
        self._value = 0.0  # jaxrace: guarded-by=self._lock

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Bounded reservoir of the most recent samples + monotonic totals.

    The reservoir keeps the tail CURRENT (a week-old latency spike must
    not sit in p99 forever); ``count``/``sum`` stay monotonic over the
    process lifetime so Prometheus-side rate() works across the wrap.
    Percentiles are nearest-rank — an observed sample, never an
    interpolation (the convention shared with StepTimer and serve).
    """

    __slots__ = ("labels", "_lock", "_samples", "_count", "_sum")

    def __init__(self, labels: tuple = _NO_LABELS, reservoir: int = 2048):
        self.labels = labels
        self._lock = threading.Lock()
        self._samples: collections.deque = collections.deque(maxlen=reservoir)
        self._count = 0    # jaxrace: guarded-by=self._lock
        self._sum = 0.0    # jaxrace: guarded-by=self._lock

    def observe(self, v: float) -> None:
        with self._lock:
            self._samples.append(float(v))
            self._count += 1
            self._sum += float(v)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def percentile(self, q: float) -> float | None:
        with self._lock:
            samples = list(self._samples)
        return percentile(samples, q) if samples else None

    def snapshot(self) -> dict:
        with self._lock:
            samples = list(self._samples)
            count, total = self._count, self._sum
        out = {"count": count, "sum": total, "samples": len(samples)}
        if samples:
            out["p50"] = percentile(samples, 50.0)
            out["p99"] = percentile(samples, 99.0)
            out["max"] = max(samples)
        return out

    def collect(self, qs: tuple = (0.5, 0.9, 0.99)) -> dict:
        """One locked copy + ONE sort serving every requested quantile —
        the scrape-path shape (snapshot()+percentile() per quantile would
        re-sort the reservoir once per value)."""
        import math

        with self._lock:
            ordered = sorted(self._samples)
            count, total = self._count, self._sum
        n = len(ordered)
        quantiles = {q: ordered[min(n, max(1, math.ceil(q * n))) - 1]
                     for q in qs} if n else {}
        return {"count": count, "sum": total, "quantiles": quantiles}


class Family:
    """All children of one metric name (one per label set)."""

    __slots__ = ("kind", "name", "help", "_children", "_lock", "_reservoir")

    def __init__(self, kind: str, name: str, help: str = "",
                 reservoir: int = 2048):
        self.kind = kind
        self.name = name
        self.help = help
        self._children: dict[tuple, object] = {}
        self._lock = threading.Lock()
        self._reservoir = reservoir

    def child(self, labels: dict | None = None):
        key = _label_key(labels)
        with self._lock:
            got = self._children.get(key)
            if got is None:
                cls = {"counter": Counter, "gauge": Gauge}.get(self.kind)
                got = Histogram(key, self._reservoir) if cls is None \
                    else cls(key)
                self._children[key] = got
            return got

    def children(self) -> list:
        with self._lock:
            return [self._children[k] for k in sorted(self._children)]


class MetricsRegistry:
    """Thread-safe name -> :class:`Family` table with get-or-create
    accessors.  Use the process-wide default via :func:`get_registry`;
    construct private instances only in tests."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: dict[str, Family] = {}

    def _family(self, kind: str, name: str, help: str,
                reservoir: int = 2048) -> Family:
        if not _NAME_RE.match(name):
            raise ValueError(f"bad metric name {name!r}")
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = Family(kind, name, help, reservoir)
                self._families[name] = fam
            elif fam.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam.kind}, "
                    f"requested {kind}")
            if help and not fam.help:
                fam.help = help
            return fam

    def counter(self, name: str, help: str = "",
                labels: dict | None = None) -> Counter:
        return self._family("counter", name, help).child(labels)

    def gauge(self, name: str, help: str = "",
              labels: dict | None = None) -> Gauge:
        return self._family("gauge", name, help).child(labels)

    def histogram(self, name: str, help: str = "",
                  labels: dict | None = None,
                  reservoir: int = 2048) -> Histogram:
        return self._family("histogram", name, help, reservoir).child(labels)

    def collect(self) -> list[Family]:
        """Families sorted by name — the renderer's stable iteration."""
        with self._lock:
            return [self._families[n] for n in sorted(self._families)]


#: the process-wide registry every subsystem shares
REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return REGISTRY


#: process-wide instrumentation switch (config.telemetry): False turns
#: spans, goodput accounting and the preemption publishing into no-ops —
#: the true zero-instrumentation baseline of the <=2%-overhead contract.
#: Registry WRITES through direct handles (serve counters) stay live:
#: they are the service's own ops surface, not optional instrumentation.
_ENABLED = True


def set_enabled(enabled: bool) -> None:
    global _ENABLED
    _ENABLED = bool(enabled)


def is_enabled() -> bool:
    return _ENABLED
