"""Goodput accounting and MFU estimation.

The question every perf PR must answer — "what fraction of wall-clock was
productive training, and if not, where did it go" — had no instrumented
answer: the trainer printed epoch seconds, the profiler needed a chip and
a human.  The :class:`GoodputAccountant` attributes the process's
wall-clock to a small closed set of buckets:

* ``step``       — productive train-step dispatch + readback
* ``compile``    — first dispatch of each compiled program (trace+XLA)
* ``checkpoint`` — save/restore/wait
* ``eval``       — validation epochs
* ``input_wait`` — the step loop blocked on the data pipeline (the
  silent killer FFCV (arxiv 2306.12517) and arxiv 2005.02130 document:
  input stalls routinely dominate training time unnoticed)
* ``idle``       — everything untracked (derived: total - tracked)

Attribution is EXCLUSIVE and nestable: entering an inner bucket pauses
the outer one's clock, so the buckets sum to tracked wall-clock by
construction (plus ``idle``, exactly total).  Per-thread stacks keep the
accounting correct on the val-overlap and checkpoint threads — with
genuinely concurrent work the per-bucket sums can legitimately exceed
wall-clock (two threads, one clock); single-threaded runs sum exactly.

MFU (model FLOPs utilization) composes the other half: model FLOPs/step
(XLA's own cost analysis where available) / step time / device peak
FLOPs, with the peak table keyed by device kind and a conservative
fallback (the smallest known TPU peak) for unknown hardware — an
estimate is always produced, labeled with its source.
"""

from __future__ import annotations

import collections
import contextlib
import threading
import time

from .registry import MetricsRegistry, get_registry

#: the closed attribution set (order = reporting order)
BUCKETS = ("step", "compile", "checkpoint", "eval", "input_wait")

# Published per-chip peak dense-matmul throughput (bf16/f32 as trained
# here).  Sources: Google Cloud TPU system-architecture tables (public).
# Matched by substring of jax's device_kind.  Single source of truth —
# bench.py imports these.
PEAK_FLOPS_BY_KIND = {
    "v5 lite": 197e12,   # v5e: 197 TFLOP/s bf16
    "v5litepod": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6 lite": 918e12,   # v6e (Trillium)
    "v6e": 918e12,
    "v4": 275e12,
    "v3": 123e12,
    "v2": 45e12,
}

# Peak HBM bandwidth per chip (B/s), same public tables, keyed identically
# — the roofline's second axis must match the chip the FLOPs table matched.
PEAK_HBM_BY_KIND = {
    "v5 lite": 819e9,
    "v5litepod": 819e9,
    "v5e": 819e9,
    "v5p": 2765e9,
    "v6 lite": 1640e9,
    "v6e": 1640e9,
    "v4": 1228e9,
    "v3": 900e9,
    "v2": 700e9,
}

#: unknown hardware (CPU dev boxes, future chips): assume the smallest
#: known TPU peak — conservative in the sense that it never inflates a
#: denominator it cannot justify, and the estimate is labeled 'fallback'
#: so nobody mistakes it for a measured-peak ratio
FALLBACK_PEAK_FLOPS = min(PEAK_FLOPS_BY_KIND.values())


def peak_flops_for(device_kind: str | None = None) -> tuple[float, str]:
    """(peak FLOP/s, source) for a device kind; source is the matched
    table key or 'fallback'."""
    if device_kind is None:
        import jax

        device_kind = jax.devices()[0].device_kind
    kind = device_kind.lower()
    for sub, val in PEAK_FLOPS_BY_KIND.items():
        if sub in kind:
            return val, sub
    return FALLBACK_PEAK_FLOPS, "fallback"


def mfu_estimate(flops_per_step: float, step_time_s: float,
                 device_kind: str | None = None) -> dict:
    """MFU = achieved FLOP/s per device / peak FLOP/s per device.

    ``flops_per_step`` is the PER-DEVICE model FLOPs of one optimizer
    step (for a whole-mesh cost, divide by the device count first);
    ``step_time_s`` is the mean wall-clock of one step.
    """
    if flops_per_step <= 0 or step_time_s <= 0:
        raise ValueError(
            f"flops_per_step and step_time_s must be > 0, got "
            f"{flops_per_step}, {step_time_s}")
    peak, source = peak_flops_for(device_kind)
    achieved = flops_per_step / step_time_s
    return {
        "mfu": achieved / peak,
        "achieved_flops_per_sec": achieved,
        "peak_flops_per_device": peak,
        "peak_source": source,
        "flops_per_step": flops_per_step,
        "step_time_s": step_time_s,
    }


def xla_step_cost(fn, *args) -> dict:
    """XLA's cost model for a jitted callable at ``args`` (concrete arrays
    or ShapeDtypeStructs): ``{"flops", "bytes"}``, None when unavailable.
    Delegates to the process-wide :mod:`telemetry.lowering` cache, so the
    MFU estimator, bench.py's roofline and the IR auditor (analysis.ir)
    all lower each program exactly once.  Shared by bench.py's roofline
    and the trainer's MFU estimator."""
    from .lowering import lower_cached

    try:
        return dict(lower_cached(fn, *args).cost())
    except Exception:
        return {"flops": None, "bytes": None}


class _Account:
    """Class-based context manager for :meth:`GoodputAccountant.account` —
    the generator-based form costs ~2x more per entry, and this sits on
    the step loop's per-iteration path (the <=2%-overhead contract)."""

    __slots__ = ("_a", "bucket")

    def __init__(self, a: "GoodputAccountant", bucket: str):
        if bucket not in a._seconds:
            raise ValueError(f"unknown goodput bucket {bucket!r} "
                             f"(one of {BUCKETS})")
        self._a = a
        self.bucket = bucket

    def __enter__(self) -> "_Account":
        a = self._a
        stack = a._stack()
        now = time.perf_counter()
        if stack:  # pause the outer bucket's clock
            outer, outer_t0 = stack[-1]
            a._credit(outer, now - outer_t0)
            stack[-1] = (outer, None)
        stack.append((self.bucket, now))
        with a._lock:
            a._counts[self.bucket] += 1
        return self

    def __exit__(self, *exc) -> bool:
        a = self._a
        stack = a._stack()
        now = time.perf_counter()
        _, t0 = stack.pop()
        a._credit(self.bucket, now - t0)
        if stack:  # resume the outer bucket's clock
            stack[-1] = (stack[-1][0], now)
        return False


#: shared stateless no-op for disabled accountants
_NOOP = contextlib.nullcontext()


class GoodputAccountant:
    """Wall-clock attribution over :data:`BUCKETS`, exclusive + nested.

    >>> acct = GoodputAccountant()
    >>> with acct.account("eval"):
    ...     with acct.account("checkpoint"):   # pauses the eval clock
    ...         save()
    >>> acct.report()["buckets"]               # sums to total (with idle)

    ``reset(enabled=False)`` turns every ``account()`` into a shared
    no-op context — the disable path the <=2%-overhead contract is
    measured against.
    """

    def __init__(self, registry: MetricsRegistry | None = None,
                 enabled: bool = True):
        self._registry = registry
        self._lock = threading.Lock()
        self._tls = threading.local()
        self.enabled = enabled
        self._t0 = time.perf_counter()
        self._seconds = {b: 0.0 for b in BUCKETS}
        self._counts = {b: 0 for b in BUCKETS}

    # ------------------------------------------------------------ lifecycle
    def reset(self, enabled: bool = True) -> None:
        """Zero the books and restart the wall clock (call at fit start)."""
        with self._lock:
            self.enabled = enabled
            self._t0 = time.perf_counter()
            self._seconds = {b: 0.0 for b in BUCKETS}
            self._counts = {b: 0 for b in BUCKETS}

    # ---------------------------------------------------------- attribution
    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _credit(self, bucket: str, seconds: float) -> None:
        with self._lock:
            self._seconds[bucket] += seconds

    def account(self, bucket: str):
        """Attribute the enclosed wall-clock to ``bucket`` (exclusive of
        any nested ``account`` regions, whose time goes to themselves).
        Returns a context manager; a shared no-op when disabled."""
        if not self.enabled:
            return _NOOP
        return _Account(self, bucket)

    def snapshot(self) -> dict:
        """Current per-bucket seconds, no derived fields, no publishing —
        the cheap read the feed governor's tick differences against its
        previous snapshot (one lock, one dict copy; safe at the log
        cadence)."""
        with self._lock:
            return dict(self._seconds)

    # ------------------------------------------------------------- reporting
    def report(self, publish: bool = True) -> dict:
        """Breakdown since the last reset.  ``idle`` is derived (total -
        tracked, clamped at 0), so in single-threaded use the buckets sum
        to ``total_s`` exactly; concurrent threads can push tracked time
        past wall-clock (two threads, one clock) — ``overlap_s`` exposes
        the excess instead of hiding it.

        ``publish`` mirrors the breakdown into registry gauges
        (``goodput_seconds{bucket=...}``, ``goodput_ratio``) so the serve
        front's ``/metrics`` exports train goodput too."""
        with self._lock:
            total = time.perf_counter() - self._t0
            seconds = dict(self._seconds)
            counts = dict(self._counts)
        tracked = sum(seconds.values())
        seconds["idle"] = max(0.0, total - tracked)
        rep = {
            "total_s": total,
            "buckets": seconds,
            "counts": counts,
            "goodput": (seconds["step"] / total) if total > 0 else 0.0,
            "overlap_s": max(0.0, tracked - total),
        }
        if publish:
            reg = self._registry or get_registry()
            for b, v in seconds.items():
                reg.gauge("goodput_seconds",
                          "wall-clock attributed per goodput bucket",
                          labels={"bucket": b}).set(v)
            reg.gauge("goodput_ratio",
                      "fraction of wall-clock in productive steps"
                      ).set(rep["goodput"])
        return rep


class FeedWindow:
    """Bounded ring of per-tick ``(busy_s, input_wait_s)`` samples — the
    windowed view of the input-stall signal the feed governor
    (data/governor.py) acts on.

    The source is the EXISTING exclusive goodput attribution: callers
    difference :meth:`GoodputAccountant.snapshot` between ticks (the log
    cadence the trainer already pays — no new host syncs) and push the
    deltas here.  ``busy_s`` is productive device-side wall-clock of the
    interval (step + compile); ``input_wait_s`` is host time blocked on
    the data pipeline.  The rolling stall fraction is
    ``sum(wait) / sum(wait + busy)`` over the ring — a per-step fraction
    would whipsaw on echo/multi-step configs where waits land on a
    subset of ticks.
    """

    def __init__(self, size: int = 16):
        if size < 1:
            raise ValueError(f"window size must be >= 1, got {size}")
        self._ring: collections.deque = collections.deque(maxlen=int(size))
        self.dropped = 0

    def __len__(self) -> int:
        return len(self._ring)

    @property
    def size(self) -> int:
        return self._ring.maxlen

    def push(self, busy_s: float, input_wait_s: float) -> None:
        if busy_s < 0 or input_wait_s < 0:
            # clock skew / accountant reset between snapshots: drop, never
            # poison the window — but COUNT the drop (a silently shrinking
            # sample base looked exactly like a healthy feed), so /metrics
            # and the doctor can tell "no stalls" from "no samples"
            self.dropped += 1
            get_registry().counter(
                "telemetry_dropped_deltas_total",
                "goodput deltas dropped for being negative "
                "(accountant reset raced the feed window)").inc()
            return
        self._ring.append((float(busy_s), float(input_wait_s)))

    def reset(self) -> None:
        self._ring.clear()

    def totals(self) -> tuple[float, float]:
        """(busy_s, input_wait_s) summed over the ring."""
        busy = sum(b for b, _ in self._ring)
        wait = sum(w for _, w in self._ring)
        return busy, wait

    def stall_fraction(self) -> float | None:
        """Rolling input-stall fraction over the ring; None until a
        sample with nonzero tracked time lands."""
        busy, wait = self.totals()
        total = busy + wait
        if total <= 0:
            return None
        return wait / total


#: process-wide accountant (reset at each fit; checkpoint/eval wiring
#: reaches it from their own modules without plumbing)
_ACCOUNTANT = GoodputAccountant()


def get_accountant() -> GoodputAccountant:
    return _ACCOUNTANT
