"""Prometheus text-exposition rendering of a :class:`MetricsRegistry`.

Text format 0.0.4 — the lingua franca every scraper parses; no client
library dependency (the container bakes none in, and the format is three
line shapes).  Counters and gauges render directly; histograms render as
Prometheus *summaries* (``name{quantile="0.5"}``, ``name_sum``,
``name_count``): the reservoir keeps observed samples, so nearest-rank
quantiles are exact over the window, whereas fixed histogram buckets
would have to be chosen per metric.

Served by ``GET /metrics`` on the serve front (serve/__main__.py) — the
single surface where serve counters, train goodput gauges, and span
percentiles all land.
"""

from __future__ import annotations

import math

from .registry import Family, MetricsRegistry, get_registry

#: served with this Content-Type (version is part of the contract)
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_QUANTILES = (0.5, 0.9, 0.99)


def _fmt(v: float) -> str:
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape(v: str) -> str:
    """Label-value escaping (0.0.4 spec): backslash, double-quote, and
    line feed — exactly these three, in this order (backslash first or
    the later escapes get double-escaped)."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    """HELP-text escaping: only backslash and line feed — the spec does
    NOT escape double-quote outside label values, and scrapers take a
    literal ``\\"`` in HELP at face value (two characters, wrong text)."""
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _labels(pairs, extra: tuple = ()) -> str:
    items = [*pairs, *extra]
    if not items:
        return ""
    return "{" + ",".join(f'{k}="{_escape(str(v))}"' for k, v in items) + "}"


def _render_family(fam: Family, lines: list[str]) -> None:
    if fam.help:
        lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
    kind = "summary" if fam.kind == "histogram" else fam.kind
    lines.append(f"# TYPE {fam.name} {kind}")
    for child in fam.children():
        if fam.kind == "histogram":
            snap = child.collect(_QUANTILES)  # one lock + one sort
            for q, v in snap["quantiles"].items():
                lines.append(
                    f"{fam.name}"
                    f"{_labels(child.labels, (('quantile', q),))} "
                    f"{_fmt(v)}")
            lines.append(f"{fam.name}_sum{_labels(child.labels)} "
                         f"{_fmt(snap['sum'])}")
            lines.append(f"{fam.name}_count{_labels(child.labels)} "
                         f"{_fmt(snap['count'])}")
        else:
            lines.append(f"{fam.name}{_labels(child.labels)} "
                         f"{_fmt(child.value)}")


def render_text(registry: MetricsRegistry | None = None) -> str:
    """The whole registry as Prometheus text exposition (ends with \\n)."""
    lines: list[str] = []
    for fam in (registry or get_registry()).collect():
        _render_family(fam, lines)
    return "\n".join(lines) + "\n" if lines else "\n"
