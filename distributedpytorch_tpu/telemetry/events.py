"""Flight recorder: the process-wide, crash-safe run-event log.

The stack's evidence was fragmented: governor.jsonl, quarantine.jsonl,
supervisor.jsonl, the flywheel ledger, COMMITTED.json and the swap
pool's counters each record one subsystem in one file with one schema.
A real incident (preempt -> topology change -> replan -> rollback ->
canary rollback) spans several process generations and hosts, and no
single file tells the story.  This module is the unifying sink: ONE
versioned line schema, appended to ``run_dir/events/<host>.<pid>.jsonl``
by every subsystem through tiny adapters at their existing choke points
— the existing ledgers are untouched (they remain each subsystem's
authoritative record; the event log is the cross-cutting index the
timeline merger and ``dptpu-doctor`` read).

Schema (version 1), one JSON object per line::

    {"v": 1, "ts_wall": <time.time()>, "ts_mono": <perf_counter()>,
     "host": str, "pid": int, "generation": int|null,
     "source": str, "kind": str, "step": int|null, "epoch": int|null,
     "payload": {...}}

``ts_mono`` orders events WITHIN a process (immune to NTP steps);
``ts_wall`` aligns processes and hosts.  The merger
(:mod:`telemetry.timeline`) reconciles the two so host clock skew can
never reorder cause and effect inside one process.  ``generation`` is
the process generation under supervision (the ``run_<N>`` index for a
trainer, the attempt number for supervisor events) — the stitching key
across restarts.

Idioms (the JsonlWriter contract, train/logging.py): the stream is
line-buffered so a crashed process keeps its tail; non-finite floats
serialize as ``null`` (strict JSON — a diverging run is exactly when
the log must stay machine-readable); a recorder failure must NEVER
kill the run it records — I/O and serialization errors are swallowed
and counted (``dropped``), and the count surfaces in bench's ``events``
block and the doctor.

Emission is host-side only and sits off the per-step path: emitters
fire at decision/boundary cadence (governor decisions, rollbacks,
checkpoint saves, restarts), never per step, and the disabled path is
one module-attribute check — the same <=2%-of-step overhead contract
every other telemetry hook carries.

Deliberately stdlib + numpy-free and importable before jax: the
supervisor (train/supervise.py) emits into it, and the supervisor must
stay a process the failure it supervises cannot take down.
"""

from __future__ import annotations

import json
import math
import os
import re
import socket
import threading
import time

#: schema version stamped on every line; bump on any key change
SCHEMA_VERSION = 1

#: the one line schema, in emission order (payload last)
EVENT_KEYS = ("v", "ts_wall", "ts_mono", "host", "pid", "generation",
              "source", "kind", "step", "epoch", "payload")

#: the emitting subsystems (the ``source`` field's closed set — the
#: timeline's episode detectors key on these)
SOURCES = ("trainer", "governor", "sentinel", "checkpoint", "preemption",
           "supervisor", "serve", "flywheel", "chaos", "fleet")

_RUN_RE = re.compile(r"run_(\d+)$")


def run_generation(run_dir: str) -> int | None:
    """The ``run_<N>`` index of a run dir (the trainer's process
    generation under supervision); None for non-run_<N> paths."""
    m = _RUN_RE.search(os.path.normpath(run_dir))
    return int(m.group(1)) if m else None


def _jsonable(v):
    """Non-finite -> null, recursively (the JsonlWriter rule)."""
    if isinstance(v, bool) or v is None:
        return v
    if isinstance(v, (int, str)):
        return v
    if isinstance(v, float):
        return v if math.isfinite(v) else None
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple, set, frozenset)):
        return [_jsonable(x) for x in v]
    # numpy scalars (and anything float()-able) without importing numpy:
    # the supervisor path must stay stdlib-importable
    try:
        f = float(v)
        return f if math.isfinite(f) else None
    except (TypeError, ValueError):
        return repr(v)


class EventLog:
    """Append-only event stream for one process at one run dir.

    One file per (host, pid): concurrent processes (multi-host, the
    supervisor beside its child) never interleave writes, and the merger
    gets per-process monotonic order for free.
    """

    def __init__(self, run_dir: str, generation: int | None = None):
        self.run_dir = run_dir
        self.generation = (run_generation(run_dir)
                           if generation is None else int(generation))
        self.host = socket.gethostname()
        self.pid = os.getpid()
        self.emitted = 0
        self.dropped = 0
        self._lock = threading.Lock()
        self.path: str | None = None
        self._f = None
        try:
            events_dir = os.path.join(run_dir, "events")
            os.makedirs(events_dir, exist_ok=True)
            self.path = os.path.join(events_dir,
                                     f"{self.host}.{self.pid}.jsonl")
            # line-buffered: a crashed run keeps its tail (the last
            # lines before the crash are the diagnosis)
            self._f = open(self.path, "a", buffering=1)
        except OSError:
            # a read-only run dir must not kill the process it records;
            # every emit() becomes a counted drop
            self.path = None

    def emit(self, source: str, kind: str, *, step: int | None = None,
             epoch: int | None = None, generation: int | None = None,
             payload: dict | None = None) -> None:
        """Append one event.  Never raises; failures count as drops."""
        rec = {
            "v": SCHEMA_VERSION,
            "ts_wall": time.time(),
            "ts_mono": time.perf_counter(),
            "host": self.host,
            "pid": self.pid,
            "generation": (self.generation if generation is None
                           else int(generation)),
            "source": source,
            "kind": kind,
            "step": None if step is None else int(step),
            "epoch": None if epoch is None else int(epoch),
            "payload": _jsonable(payload or {}),
        }
        try:
            line = json.dumps(rec, allow_nan=False)
        except (TypeError, ValueError):
            self.dropped += 1
            return
        with self._lock:
            if self._f is None:
                self.dropped += 1
                return
            try:
                self._f.write(line + "\n")
                self.emitted += 1
            except (OSError, ValueError):
                self.dropped += 1

    def block(self) -> dict:
        """The bench ``events`` block: keys always present."""
        return {"emitted": int(self.emitted), "dropped": int(self.dropped),
                "path": self.path}

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None


# --------------------------------------------------------- process state
#
# A stack, not a bare singleton: a flywheel process configures its work
# dir, then each in-process fit configures its own run_<N> — the fit's
# events land under the fit's run dir, and release() restores the
# flywheel's log when the trainer closes.

_STACK: list[EventLog] = []
_STACK_LOCK = threading.Lock()


def configure(run_dir: str, generation: int | None = None) -> EventLog:
    """Open (and make current) an event log under ``run_dir``."""
    log = EventLog(run_dir, generation=generation)
    with _STACK_LOCK:
        _STACK.append(log)
    return log


def release(log: EventLog | None) -> None:
    """Close ``log`` and restore the previously configured one."""
    if log is None:
        return
    log.close()
    with _STACK_LOCK:
        if log in _STACK:
            _STACK.remove(log)


def current() -> EventLog | None:
    return _STACK[-1] if _STACK else None


def emit(source: str, kind: str, *, step: int | None = None,
         epoch: int | None = None, generation: int | None = None,
         payload: dict | None = None) -> None:
    """Module-level adapter every subsystem calls: a no-op (one list
    check) when no log is configured — the disabled path's whole cost."""
    if not _STACK:
        return
    log = _STACK[-1]
    log.emit(source, kind, step=step, epoch=epoch,
             generation=generation, payload=payload)


def events_block() -> dict:
    """The bench record's ``events`` block from the current log — keys
    ALWAYS present, all None when no log is configured (telemetry off:
    the recovery/plan null convention)."""
    log = current()
    if log is None:
        return {"emitted": None, "dropped": None, "path": None}
    return log.block()


def read_events_file(path: str) -> list[dict]:
    """Parse one event file, tolerating a torn last line (the crash-safe
    read half: a SIGKILLed process's final partial write is dropped, not
    fatal)."""
    out: list[dict] = []
    try:
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail / partial write
                if isinstance(rec, dict) and rec.get("v") == SCHEMA_VERSION:
                    out.append(rec)
    except OSError:
        pass
    return out
