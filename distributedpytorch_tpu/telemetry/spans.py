"""Host-side spans that nest — and appear in device traces by the same name.

A :func:`span` is a context manager that (1) times the enclosed host
region, (2) records the duration into the registry histogram
``span_seconds{span="<path>"}`` where ``<path>`` is the slash-joined
nesting (``fit/epoch/checkpoint``), and (3) enters a
``jax.profiler.TraceAnnotation`` with the same path, so the identical
names show up inside XPlane device traces (xprof / tensorboard) next to
the ops they bracket.  One name, three views: registry percentiles,
Prometheus summary, device timeline.

Nesting is thread-local: concurrent threads (the val-overlap thread, the
serve worker) each carry their own span stack, so paths never interleave
across threads.
"""

from __future__ import annotations

import contextlib
import threading
import time

from .registry import MetricsRegistry, get_registry, is_enabled

_tls = threading.local()


def current_span() -> str:
    """Slash-joined path of the active span stack ('' outside any span)."""
    return "/".join(getattr(_tls, "stack", ()))


@contextlib.contextmanager
def span(name: str, registry: MetricsRegistry | None = None):
    """Time a named, nestable host region; mirror it into device traces.

    >>> with span("epoch"):
    ...     with span("checkpoint"):   # records span="epoch/checkpoint"
    ...         ckpt.save(...)

    A profiler failure degrades (the host region still runs and records);
    with telemetry disabled (:func:`registry.set_enabled`) the whole span
    is a no-op.
    """
    if not is_enabled():
        yield name
        return
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(name)
    path = "/".join(stack)
    annotation = None
    try:
        # deferred import: jax must not load just because telemetry did
        import jax

        annotation = jax.profiler.TraceAnnotation(path)
        annotation.__enter__()
    except Exception:
        annotation = None  # never corrupt the stack or kill the region
    t0 = time.perf_counter()
    try:
        yield path
    finally:
        dt = time.perf_counter() - t0
        if annotation is not None:
            try:
                annotation.__exit__(None, None, None)
            except Exception:
                pass
        stack.pop()
        (registry or get_registry()).histogram(
            "span_seconds", "host-side span durations by nested path",
            labels={"span": path}).observe(dt)
